"""The LLM continuous-batching scheduler: admission, chunked prefill,
pipelined/mega decode windows, paged-KV block accounting, and
retirement. Mixin methods on InferenceEngine — split from
``engine.py`` along its scheduler seams (r4 VERDICT weak #10)."""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import InvalidStateError

from typing import Any, Optional

import numpy as np

from gofr_tpu import faults
from gofr_tpu.analysis import lockcheck
from gofr_tpu.serving.types import (
    _ActiveSeq,
    _GenRequest,
    _PrefillState,
    GenerationResult,
)

# Thread attribute carrying the scheduler epoch the thread was started
# under (each thread brands only itself, so there is no cross-thread
# write to race).
_EPOCH_ATTR = "gofr_sched_epoch"


class SchedulerSuperseded(BaseException):
    """The supervisor restarted the engine around this (previously
    wedged) scheduler thread: its epoch is stale, so it must exit
    WITHOUT touching engine state or draining — the supervisor already
    salvaged or failed every request it owned. BaseException on purpose:
    nothing between the dispatch seams and the loop may catch it."""


class SchedulerMixin:
    """The scheduler thread's entire dataplane-facing loop."""

    # -- the mixin contract (mypy strict scope) ------------------------
    # Everything below is provided by InferenceEngine.__init__ /
    # _init_llm_serving_state (state) or by the sibling mixins
    # (compiled-program callables). Declared here so the strict type
    # gate checks this module's OWN logic against a written-down
    # contract instead of guessing at the facade's shape.
    _running: bool
    _epoch: int
    _fatal: Optional[BaseException]
    _drained: bool
    _sched_idle: bool
    _restart_pending: bool
    _queued_tokens: int
    _prefix_lookups: int
    _prefix_hit_tokens: int
    _prefill_chunk_steps: int
    _table_dirty: bool
    _slot_state_dirty: bool
    _seeds_dirty: bool
    _lockstep: bool
    kv_block: int
    max_len: int
    mega_windows: int
    tier_role: str
    prefix_evict_watermark: int
    effective_evict_watermark: int
    prefix_evict_hbm_frac: float
    _wm_fruitless: "Optional[tuple[int, int]]"
    n_slots: int
    pipeline_depth: int
    prefill_batch: int
    prefill_chunk: int
    prefill_depth: int
    spec_tokens: int
    top_logprobs: int
    window_k: int
    enable_penalties: bool
    model_name: str
    _submit_lock: threading.Lock
    _idle_evt: threading.Event
    _work: threading.Event
    _pending: Any  # lifecycle.ClassPriorityQueue[_GenRequest]
    _wait_kv: Any  # deque[_GenRequest]
    _slots: "list[Optional[_ActiveSeq]]"
    _prefilling: "dict[int, _PrefillState]"
    _prefill_emits: list
    _replay: "list[_GenRequest]"
    _tenant_queued: "dict[str, int]"
    _slot_blocks: "list[list[int]]"
    _dispatched_tokens: "list[int]"
    _lora_gen: "list[int]"
    _allocator: Any  # ops.kv_cache.BlockAllocator
    _radix: Any  # Optional[serving.radix_cache.RadixPrefixIndex]
    _prefix_pool: Any  # Optional[serving.prefix_cache.PrefixPool]
    _supervisor: Any
    _handoff: Any
    _tier_exporter: Any
    _tier_imports: Any  # deque[ops.kv_cache.KVBlockPayload]
    _tier_import_done: Any  # dict[id(payload) -> threading.Event]
    _tier_exports: Any  # deque[(token_ids, result_box, threading.Event)]
    _watchdog: Any
    _metrics: Any
    _obs: Any  # serving.observability.RequestObservability
    _loop_prof: Any  # Optional[serving.loop_profiler.LoopProfiler]
    _tenant_ledger: Any  # Optional[serving.tenant_ledger.TenantLedger]
    _ledger: Any  # Optional[serving.device_telemetry.HBMLedger]
    _slo: Any  # Optional[serving.slo.SLOEngine]
    _brownout: Any  # Optional[serving.brownout.BrownoutController]
    _control: Any  # Optional[serving.control_plane.ControlPlane]
    _compiles: Any  # serving.device_telemetry.CompileTracker
    _logger: Any
    _tput: Any  # lifecycle.AggregateThroughput
    tokenizer: Any
    cache: Any
    params: Any
    _jax: Any
    _jnp: Any
    _up: Any  # host→device placement callable
    _table_host: Any  # np.ndarray [S, max_blocks] mirror
    _seeds_host: Any
    _noff_host: Any
    _aids_host: Any
    _bidx_host: Any
    _bval_host: Any
    # Device-resident slot planes (jax arrays).
    _tokens_dev: Any
    _logps_dev: Any
    _nsteps_dev: Any
    _seeds_dev: Any
    _noff_dev: Any
    _aids_dev: Any
    _active_dev: Any
    _temps_dev: Any
    _topp_dev: Any
    _greedy_dev: Any
    _fpen_dev: Any
    _ppen_dev: Any
    _pcounts_dev: Any
    _bidx_dev: Any
    _bval_dev: Any
    _topi_dev: Any
    _topl_dev: Any
    _history_dev: Any
    # Compiled-program callables (LLMProgramsMixin) and engine methods
    # this loop calls across the facade.
    _prefill_chunk_step: Any
    _prefill_chunk_step_hist: Any
    _prefill_multi_chunk: Any
    _prefill_multi_chunk_hist: Any
    _decode_window: Any
    _spec_window: Any
    _mega_window: Any
    _mega_spec_window: Any
    # Compile-tracked paged-pool jits (engine._init_llm_serving_state
    # wraps ops.kv_cache.paged_{copy,insert,extract,move}_block per
    # engine; extract/move are the device-leg tier-transfer pair).
    _paged_copy_block: Any
    _paged_insert_block: Any
    _paged_extract_block: Any
    _paged_move_block: Any
    _block_sharding: Any  # Optional[NamedSharding] for inbound planes
    _note_dequeued: Any
    _set_state: Any
    hbm_headroom_ratio: Any
    _kv_pool_counts: Any
    try_handoff: Any

    def _check_superseded(self) -> None:
        """Raise :class:`SchedulerSuperseded` when this thread's branded
        epoch no longer matches the engine's — i.e. the supervisor
        abandoned this thread mid-wedge and a new scheduler owns the
        state. Called at the seams where a wedged step would resume."""
        epoch = getattr(threading.current_thread(), _EPOCH_ATTR, None)
        if epoch is not None and epoch != self._epoch:
            raise SchedulerSuperseded

    def _scheduler_loop(self) -> None:
        error: BaseException | None = None
        # Brand this thread with the epoch it was started under: if the
        # supervisor abandons it (wedged device step) and restarts the
        # engine, the bumped engine epoch makes every later touch from
        # this thread raise SchedulerSuperseded instead of corrupting
        # the new scheduler's state.
        epoch = self._epoch
        setattr(threading.current_thread(), _EPOCH_ATTR, epoch)
        # Windows are PIPELINED `pipeline_depth` deep: dispatch window n+D
        # before fetching window n's tokens. The ~66ms host↔device roundtrip
        # (network-attached relay) is latency, not bandwidth — overlapping
        # D fetches with compute takes llama-1b from 518 (serial) to 987
        # (D=1) tok/s/chip and beyond; the floor becomes device step time.
        from collections import deque

        inflight: deque = deque()  # _dispatch_window return tuples
        # Loop profiler (serving/loop_profiler.py): one clock stamp per
        # PHASE BOUNDARY per pass (window granularity — GL011's
        # discipline), attributed into per-phase rolling stats, the
        # utilization / host-overhead gauges, and the stall detector.
        # Off (TPU_LOOP_PROFILE=0) = one `is not None` per boundary.
        prof = self._loop_prof
        try:
            while self._running and self._epoch == epoch:
                # begin_pass also CLOSES the previous pass: residual
                # time since its last stamp lands in "other", so the
                # per-phase durations sum to pass wall time exactly.
                if prof is not None:
                    prof.begin_pass(self._obs.now())
                # Progress heartbeat: the watchdog trips when this loop
                # stalls (hung device step, wedged relay) for longer than
                # its wall-time bound. Idle iterations pet every ≤20 ms.
                if self._watchdog is not None:
                    self._watchdog.pet()
                # Fault seam: a test's armed action here can stall the
                # whole loop (watchdog coverage) or fail one iteration.
                faults.fire("scheduler.window", engine=self)
                self._check_superseded()
                # Lifecycle reap: cancelled/disconnected/deadline-expired
                # sequences retire HERE, once per loop iteration, so a
                # dead stream's KV blocks free within one decode window.
                self._reap_lifecycle()
                if prof is not None:
                    prof.lap("reap", self._obs.now())
                # Tenant attribution (serving/tenant_ledger.py): one
                # KV-occupancy integration pass per loop iteration —
                # one clock read shared by every live slot, never per
                # token. Off (TPU_TENANT_LEDGER=0) = this one check.
                if self._tenant_ledger is not None:
                    self._ledger_tick()
                    if prof is not None:
                        prof.lap("ledger", self._obs.now())
                # Brownout control loop (serving/brownout.py): ONE
                # evaluation per scheduler pass — the GL011-disciplined
                # cadence the ladder's sustain windows assume. Off
                # (TPU_BROWNOUT=0) = this one check.
                if self._brownout is not None:
                    self._brownout_tick()
                    if prof is not None:
                        prof.lap("brownout", self._obs.now())
                # Control plane (serving/control_plane.py): ONE guarded
                # pass over every registered signal + the three closed
                # loops, right after the sensors it consumes ticked.
                # Off (TPU_CONTROL_PLANE=0) = this one check; evaluate
                # never raises (a lying sensor degrades its loop to
                # observe-only instead of wedging this pass).
                if self._control is not None:
                    self._control.evaluate(self._obs.now())
                    if prof is not None:
                        prof.lap("control", self._obs.now())
                if self.kv_block:
                    # Proactive prefix-eviction sweep: keep the free
                    # list above the watermark so admission finds free
                    # blocks instead of pre-evicting synchronously.
                    self._radix_watermark_sweep()
                    if prof is not None:
                        prof.lap("sweep", self._obs.now())
                # One chunk step per iteration, interleaved 1:1 with decode
                # windows: a long prompt's prefill proceeds in bounded slices
                # and never freezes active token streams (VERDICT r1 #9).
                progressed = self._dispatch_prefill_chunk(lap_import=True)
                # Wave admission: on a cold start or a retirement wave the
                # 1:1 interleave would refill capacity one chunk per window
                # — at 64 slots that is ~15 windows of a mostly-idle device
                # (measured: the 64-slot bench lost ~2 s per wave to it).
                # While live streams fill under a quarter of the slots, the
                # marginal inter-token latency of another ~1-4 ms chunk step
                # is noise next to the idle capacity, so keep draining; past
                # that, protect the live streams' latency (1:1 again).
                if progressed:
                    while (
                        sum(1 for s in self._slots if s is not None) * 4
                        < self.n_slots
                        and self._dispatch_prefill_chunk()
                    ):
                        pass
                if prof is not None:
                    prof.lap("prefill", self._obs.now())
                self._flush_prefill_emits()
                if prof is not None:
                    prof.lap("emit_flush", self._obs.now())
                any_active = any(s is not None for s in self._slots)
                if not any_active and not inflight:
                    if not progressed and not self._prefill_emits:
                        # Publish "verifiably idle" under the submit lock:
                        # the graceful drain trusts this flag, and the
                        # lock means no submission can race past it.
                        with self._submit_lock:
                            if self._pending.empty() and not self._wait_kv:
                                self._sched_idle = True
                                self._idle_evt.set()
                        self._work.wait(timeout=0.02)
                        self._work.clear()
                        if prof is not None:
                            prof.lap("idle", self._obs.now())
                    continue
                with self._submit_lock:
                    self._sched_idle = False
                # Dispatch only while some active slot still has budget
                # beyond what in-flight windows already cover — a wave of
                # same-length requests otherwise ends with `depth` pure-
                # overshoot windows whose tokens are all discarded.
                # (tokens_in_flight counts the GUARANTEED k emissions per
                # window + the prefill token; emitted = in_flight - 1, so
                # dispatch while in_flight <= budget. eos/stop retirements
                # end earlier via processing; speculation only ever emits
                # MORE per window than the guarantee.)
                wants_more = any_active and any(
                    s is not None
                    and s.tokens_in_flight <= s.request.remaining_new_tokens
                    for s in self._slots
                )
                if wants_more:
                    inflight.append(self._dispatch_window())
                    if prof is not None:
                        prof.lap("dispatch", self._obs.now())
                processed = False
                while len(inflight) > (self.pipeline_depth if wants_more else 0):
                    self._process_window(*inflight.popleft())
                    processed = True
                if processed and prof is not None:
                    # The designated device-wait seam: the fetch block
                    # inside _process_window is where the loop
                    # legitimately waits on the device — everything
                    # else busy counts as host overhead (GL019 is the
                    # static twin of this attribution).
                    prof.lap("device_window", self._obs.now())
        except SchedulerSuperseded:
            # The supervisor restarted the engine around this wedged
            # thread: a new scheduler owns every structure, and the
            # supervisor already salvaged/failed this thread's requests.
            # Exit with NO drain — failing futures here would double-
            # resolve requests the new scheduler is replaying.
            return
        except BaseException as exc:  # noqa: BLE001 — must not strand futures
            # A scheduler crash (e.g. a kernel that fails to compile on this
            # hardware) must fail every caller, not hang them until timeout.
            # The flag writes hold the submit lock like every other writer:
            # _enqueue's fatal/running checks must never see a half-
            # published death.
            error = exc
            with self._submit_lock:
                if self._epoch != epoch:
                    # An abandoned (wedged) thread whose stuck call
                    # finally RAISED: the engine was restarted around it,
                    # so these flags belong to the new scheduler — exit
                    # without touching anything.
                    return
                self._fatal = exc
                self._running = False
            self._set_state("DEGRADED")
            if self._logger is not None:
                self._logger.errorf("engine scheduler died: %s", exc)
        # Drain: fail queued requests AND active slots so no awaiting caller
        # hangs on an unresolved future / unterminated stream. The submit
        # lock closes the race where a submitter enqueues between the
        # scheduler's exit and this drain.
        reason: BaseException = error or RuntimeError("engine stopped")
        # With a supervisor attached and a restart coming (fatal exit, or
        # a watchdog-trip teardown marked by _restart_pending), RETRYABLE
        # requests are salvaged for replay instead of failed: their
        # futures/streams stay open and the supervisor requeues them on
        # the restarted engine. Non-retryable ones (cancelled, expired,
        # prefix registrations) fail through the existing terminal path.
        # A STOPPING supervisor accepts no salvage — nothing would ever
        # requeue it (a crash racing engine.close() must fail its
        # requests, not park them forever).
        sup = self._supervisor
        salvaging = (
            sup is not None
            and not sup.stopping
            and (error is not None or self._restart_pending)
        )
        salvaged: list[_GenRequest] = []

        handoff_after: list[_GenRequest] = []

        def _terminal(req: _GenRequest) -> None:
            # done() + InvalidStateError guard: an async caller may have
            # cancelled the future already.
            try:
                if not req.future.done():
                    req.future.set_exception(reason)
            except InvalidStateError:  # cancelled concurrently
                pass
            req.stream.put(None)
            self._obs_finish(req, "error", "engine_stopped")

        def _fail(req: _GenRequest) -> None:
            if salvaging and req.retryable():
                salvaged.append(req)
                return
            # Replica-pool handoff: with no supervisor to replay locally
            # (or a stopping one), a still-retryable request can instead
            # continue on a SIBLING replica — the pool requeues it with
            # its stream/future intact. Deferred past the submit-lock
            # release below: adoption takes the SIBLING engine's submit
            # lock, and two replicas draining into each other under
            # their own locks would deadlock. Only unplaceable requests
            # get the terminal error.
            if (
                not salvaging
                and self._handoff is not None
                and not req.aid
                and not req.pin_replica
                and req.retryable()
            ):
                handoff_after.append(req)
                return
            _terminal(req)

        # Block on in-flight windows first: returning from stop with device
        # computations + async host copies still outstanding races
        # interpreter teardown (observed as a runtime-client thread panic
        # at exit). This barrier is also where a WEDGED device leaves the
        # thread parked — everything after it runs under ONE submit-lock
        # hold with one epoch check, so the supervisor's abandonment
        # (epoch bump + salvage, also under the lock) strictly either
        # precedes this drain (it returns untouched) or follows it (the
        # salvage sees emptied structures and the already-parked replay
        # list) — never interleaves into double-salvage or stranding.
        while inflight:
            emitted = inflight.popleft()[0]
            try:
                np.asarray(emitted)  # graftlint: disable=GL001 — shutdown barrier, not a hot-path sync
            except Exception:  # graftlint: disable=GL006 — device may already be down; any failure here means the fetch is moot
                pass
        with self._submit_lock:
            if self._epoch != epoch:
                # Superseded at (or while parked in) the barrier: the
                # supervisor owns every request now.
                return
            self._drained = True
            self._queued_tokens = 0
            self._tenant_queued.clear()
            if self._tenant_ledger is not None:
                self._tenant_ledger.reset_queued()
            while not self._pending.empty():
                try:
                    req = self._pending.get_nowait()
                except queue.Empty:
                    break
                _fail(req)
            for i, seq in enumerate(self._slots):
                if seq is None:
                    continue
                _fail(seq.request)
                self._release_slot(i)
            for slot, st in list(self._prefilling.items()):
                _fail(st.request)
                del self._prefilling[slot]
            while self._wait_kv:
                _fail(self._wait_kv.popleft())
            self._prefill_emits.clear()
            if salvaged:
                self._replay.extend(salvaged)
        # Handoffs run with the submit lock RELEASED (see _fail above).
        for req in handoff_after:
            if not self.try_handoff(req):
                _terminal(req)
        # Wake any graceful drain blocked on the idle event: whether this
        # exit was clean or fatal, there is nothing left to wait for.
        self._idle_evt.set()
        # Crash notification LAST: the supervisor may start restarting the
        # moment it hears, and the salvage above must already be parked.
        if error is not None and self._supervisor is not None:
            self._supervisor.notify_crash(error)

    # ------------------------------------------------------------------
    # observability (serving/observability.py)
    # ------------------------------------------------------------------

    def _obs_finish(
        self, req: _GenRequest, outcome: str, reason: str = ""
    ) -> None:
        """Close a request's timeline from a terminal path. Latched by
        the timeline itself, so racing terminal paths (reap vs drain vs
        supervisor fail) summarize exactly once; no-op when the
        observability layer is off. The tenant ledger's exactly-once
        attribution rides the same seam (its own latch on the request)."""
        tl = req.timeline
        if tl is not None:
            tl.finish(outcome, reason, output_tokens=len(req.token_ids))
        if self._tenant_ledger is not None:
            self._tenant_ledger.finish_request(req, outcome)

    def _ledger_tick(self) -> None:
        """Snapshot (tenant, blocks held) for every slot with a live
        block table — decoding AND mid-prefill — and hand it to the
        tenant ledger's occupancy integrator with ONE clock read.
        Unpaged engines still tick (token/outcome attribution needs a
        clock base), just with no rows."""
        led = self._tenant_ledger
        rows: list[tuple[str, int]] = []
        if self.kv_block:
            for i, seq in enumerate(self._slots):
                if seq is not None and self._slot_blocks[i]:
                    rows.append(
                        (seq.request.tenant, len(self._slot_blocks[i]))
                    )
            for slot, st in self._prefilling.items():
                if self._slot_blocks[slot]:
                    rows.append(
                        (st.request.tenant, len(self._slot_blocks[slot]))
                    )
        led.tick(self._obs.now(), rows)

    def _brownout_tick(self) -> None:
        """Feed the controller its two inputs — the worst 5m burn rate
        and the HBM headroom ratio — once per scheduler pass. Both are
        host arithmetic already in hand (one locked ring read, one
        allocator-count division); the controller reads its own clock
        once inside ``evaluate``."""
        slo = self._slo
        burn = slo.worst_burn("5m") if slo is not None else 0.0
        headroom = (
            self.hbm_headroom_ratio() if self._ledger is not None else None
        )
        self._brownout.evaluate(burn, headroom)

    # ------------------------------------------------------------------
    # request-lifecycle reap (cancellation + deadlines)
    # ------------------------------------------------------------------

    @staticmethod
    def _reap_reason(req: _GenRequest) -> Optional[str]:
        """The ONE retirement predicate ("cancelled" | "deadline" |
        None) — every reap site must route through this so a new
        retirement reason can never be missed by one of them."""
        if req.cancel.cancelled or req.future.cancelled():
            return "cancelled"
        if req.deadline is not None and req.deadline.expired():
            return "deadline"
        return None

    def _reap_request(self, req: _GenRequest, slot: int = -1) -> bool:
        """Retire ``req`` if its cancel token tripped (client gone) or
        its deadline expired. Returns True when retired: the future gets
        its terminal error, the stream its sentinel, and ``slot`` (when
        ≥0) is released — paged mode returns its KV blocks to the pool.
        """
        reason = self._reap_reason(req)
        if reason is None:
            return False
        try:
            if not req.future.done():
                if reason == "deadline":
                    from gofr_tpu.errors import ErrorDeadlineExceeded

                    req.future.set_exception(ErrorDeadlineExceeded(
                        f"after {len(req.token_ids)} generated token(s)"
                    ))
                else:
                    from gofr_tpu.errors import ErrorRequestCancelled

                    req.future.set_exception(ErrorRequestCancelled())
        except InvalidStateError:  # caller cancelled concurrently
            pass
        req.stream.put(None)
        self._obs_finish(req, reason)
        if slot >= 0:
            self._release_slot(slot)
        if self._metrics is not None:
            name = (
                "app_tpu_deadline_exceeded_total" if reason == "deadline"
                else "app_tpu_requests_cancelled_total"
            )
            self._metrics.increment_counter(
                name, "model", self.model_name
            )
        if self._logger is not None:
            self._logger.debugf(
                "retired request (%s) after %d token(s)",
                reason, len(req.token_ids),
            )
        return True

    def _reap_lifecycle(self) -> None:
        """One pass over every live request the outside world may have
        abandoned: active decode slots, slots mid-prefill, and requests
        parked for KV blocks. Queued requests are checked at admission
        (``_dispatch_prefill_chunk``) where they are popped anyway."""
        for i, seq in enumerate(self._slots):
            if seq is not None:
                self._reap_request(seq.request, slot=i)
        for slot, st in list(self._prefilling.items()):
            if self._reap_request(st.request, slot=slot):
                del self._prefilling[slot]
        if self._wait_kv and any(
            self._reap_reason(r) is not None for r in self._wait_kv
        ):
            kept = [r for r in self._wait_kv if not self._reap_request(r)]
            self._wait_kv.clear()
            self._wait_kv.extend(kept)

    # ------------------------------------------------------------------
    # paged-KV block allocator (host side; kv_block > 0 only)
    # ------------------------------------------------------------------

    def _publish_prefix_gauge(self) -> None:
        """Refresh ``app_tpu_prefix_cached_blocks`` — call after ANY
        path that shrinks or grows the radix index (retire-insert,
        pressure eviction, adapter purge), or dashboards report a
        stale count until some unrelated request retires."""
        if self._metrics is not None and self._radix is not None:
            self._metrics.set_gauge(
                "app_tpu_prefix_cached_blocks",
                self._radix.n_cached_blocks,
                "model", self.model_name,
            )

    def _alloc_block(self) -> Optional[int]:
        """One free pool block, evicting unreferenced radix-cached
        blocks (LRU) when the free list is dry — cached prefixes are a
        best-effort optimization and must never starve live requests."""
        bid = self._allocator.alloc()
        if bid is None and self._radix is not None and self._radix.evict(1):
            bid = self._allocator.alloc()
            self._publish_prefix_gauge()
        return bid

    def _ensure_blocks(self, slot: int, tokens: int) -> bool:
        """Grow ``slot``'s allocation to cover ``tokens`` logical tokens.
        Returns False when the pool is exhausted (caller defers or fails)
        — rolling back any partial grab, so a waiting request can never
        strand blocks on an idle slot while live streams starve."""
        B = self.kv_block
        target = min(
            (min(tokens, self.max_len) + B - 1) // B,
            self._table_host.shape[1],
        )
        row = self._slot_blocks[slot]
        start_len = len(row)
        shortfall = (target - start_len) - self._allocator.n_free
        if shortfall > 0 and self._radix is not None:
            # Batch the pressure eviction: one LRU sweep for the whole
            # grow instead of a full-trie scan per allocated block (the
            # per-alloc evict(1) in _alloc_block stays as the fallback).
            if self._radix.evict(shortfall):
                self._publish_prefix_gauge()
        while len(row) < target:
            blk = self._alloc_block()
            if blk is None:
                while len(row) > start_len:  # rollback the partial grab
                    rb = row.pop()
                    self._table_host[slot, len(row)] = 0
                    self._allocator.decref(rb)
                return False
            self._table_host[slot, len(row)] = blk
            row.append(blk)
            self._table_dirty = True
        if self._metrics is not None and len(row) != start_len:
            self._metrics.set_gauge(
                "app_tpu_kv_blocks_free", self._allocator.n_free,
                "model", self.model_name,
            )
        return True

    def _release_blocks(
        self, slot: int, adopted: "frozenset[int] | set[int]" = frozenset()
    ) -> None:
        """Drop ``slot``'s references on its table row (skipping blocks
        whose reference the radix index just ADOPTED) and clear the row.
        Refcount-0 blocks return to the free list; blocks still aliased
        by other slots or cached in the index survive."""
        row = self._slot_blocks[slot]
        if row:
            for blk in row:
                if blk not in adopted:
                    self._allocator.decref(blk)
            self._slot_blocks[slot] = []
            self._table_host[slot, :] = 0
            self._table_dirty = True
        self._dispatched_tokens[slot] = 0

    def _cache_prompt_blocks(self, req: _GenRequest, slot: int) -> set[int]:
        """Insert a retiring request's now-immutable FULL prompt blocks
        into the radix index instead of freeing them (the automatic
        prefix cache's write path). Only blocks wholly covered by the
        prompt qualify — the boundary partial block and decode blocks
        carry generated tokens; and only a COMPLETED prefill is indexed
        (``effective_prompt_len`` is set at finalize). Returns the block
        ids whose reference the index adopted."""
        if req.prefix_store or req.effective_prompt_len <= 0:
            return set()
        if req.aid and req.lora_gen != self._lora_gen[req.aid]:
            # The adapter slot was reloaded since admission: these blocks
            # hold K/V from superseded weights — never index them.
            return set()
        row = self._slot_blocks[slot]
        n_full = min(len(req.prompt_ids) // self.kv_block, len(row))
        if n_full <= 0:
            return set()
        flags = self._radix.insert(
            req.prompt_ids, row[:n_full], req.aid
        )
        if req.aid and req.lora_gen != self._lora_gen[req.aid]:
            # load/unload_lora raced retirement: its generation bump
            # landed after the staleness check above, and its purge may
            # have run BEFORE our insert — leaving just-indexed blocks
            # that hold the superseded weights' K/V. The bump always
            # precedes the purge, so re-checking after the insert
            # catches every interleaving: purge the aid again ourselves.
            # Refcount accounting stays exact either way — the purge
            # consumes the index's reference for adopted blocks (so the
            # caller must still skip them) and the incumbent's for
            # duplicates (the caller still drops its own).
            self._radix.purge_aid(req.aid)
        return {row[j] for j, f in enumerate(flags) if f}

    def _alias_prefix_blocks(
        self, slot: int, req: _GenRequest, pids: list[int]
    ) -> int:
        """Admission-time zero-copy prefix hit: walk the radix index for
        the longest cached full-block prefix of ``pids``, alias those
        physical blocks into ``slot``'s table (refcount bump, no device
        copy), and return the token count the chunked prefill may skip.

        Boundary copy-on-write: when the cached prefix covers the ENTIRE
        prompt, the finalize chunk still re-writes the last prompt
        position (it samples the first token there), so the final
        aliased block is duplicated via ``paged_copy_block`` and the
        table points at the private copy — a slot never writes a block
        with refcount > 1. If no block is free for the copy, the last
        aliased block is simply surrendered and prefilled fresh."""
        radix = self._radix
        if radix is None or req.prefix_store:
            return 0
        # lookup returns with one allocator reference HELD per block
        # (taken under the radix lock, so a racing purge_aid cannot free
        # a block before we reference it); each reference transfers to
        # the slot's table below — blocks we end up not aliasing must be
        # decref'd here.
        blocks, matched = radix.lookup(pids, req.aid)
        self._prefix_lookups += 1
        hit = bool(blocks)
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_prefix_lookup_total",
                "model", self.model_name,
                "result", "hit" if hit else "miss",
            )
        if not hit:
            return 0
        B = self.kv_block
        for bid in blocks[self._table_host.shape[1]:]:
            self._allocator.decref(bid)  # beyond the slot table's width
        blocks = blocks[: self._table_host.shape[1]]
        matched = len(blocks) * B
        done = min(matched, len(pids) - 1)
        row = self._slot_blocks[slot]  # free slot → empty row
        for j, bid in enumerate(blocks):
            self._table_host[slot, j] = bid
            row.append(bid)
        self._table_dirty = True
        if done < matched:
            # Whole prompt cached: COW the boundary block the finalize
            # chunk will write into.
            src = row[-1]
            dst = self._alloc_block()
            if dst is None:
                row.pop()
                self._table_host[slot, len(row)] = 0
                self._allocator.decref(src)
                done = min(len(row) * B, len(pids) - 1)
            else:
                # Table upload can ride the next _push_table — the copy
                # only touches pool planes, not the table (compile-
                # tracked: the COW jit is one program per geometry).
                self.cache = self._paged_copy_block(
                    self.cache,
                    self._up(np.int32(src)),
                    self._up(np.int32(dst)),
                )
                row[-1] = dst
                self._table_host[slot, len(row) - 1] = dst
                self._allocator.decref(src)
        return done

    def _release_slot(self, slot: int) -> None:
        """Free a slot and (paged mode) drop its block references —
        indexing finished prompts' full blocks in the radix cache first,
        so repeated prefixes admission-alias instead of re-prefilling."""
        seq = self._slots[slot]
        self._slots[slot] = None
        self._slot_state_dirty = True
        if self.kv_block:
            adopted: set[int] = set()
            if self._radix is not None and seq is not None:
                adopted = self._cache_prompt_blocks(seq.request, slot)
            self._release_blocks(slot, adopted)
        if self._metrics is not None and self.kv_block:
            self._metrics.set_gauge(
                "app_tpu_kv_blocks_free", self._allocator.n_free,
                "model", self.model_name,
            )
            self._publish_prefix_gauge()

    def _push_table(self) -> None:
        """Upload the block-table mirror if admission/top-up dirtied it."""
        if self.kv_block and self._table_dirty:
            self.cache = self.cache._replace(
                block_table=self._up(self._table_host)
            )
            self._table_dirty = False

    # ------------------------------------------------------------------
    # disaggregated prefill/decode tier (service/replica_pool.py)
    # ------------------------------------------------------------------

    def _apply_tier_imports(self) -> None:
        """Apply queued tier-transfer payloads (decode tier): write each
        shipped block into a freshly allocated pool block and insert it
        into the radix index under its content key — the transferred
        request (already requeued by ``handoff_prefilled``) then
        admission-aliases them zero-copy like any prefix hit. Runs on
        the scheduler thread only: the cache planes are donated to
        in-flight dispatches, so no other thread may touch them.
        Anything that cannot apply (no radix, geometry drift after a
        warm restart, pool dry) is dropped and the request simply
        re-prefills — the fused fallback, never a wrong answer."""
        while self._tier_imports:
            try:
                payload = self._tier_imports.popleft()
            except IndexError:  # raced handoff_prefilled's un-stash
                return
            self._import_payload(payload)
            # Release an import_payload(wait_s=...) caller parked on
            # this payload's apply (the pool's remote-source pull): the
            # latch is set AFTER the radix insert, so a submit that
            # follows the wait deterministically alias-hits.
            done = self._tier_import_done.pop(id(payload), None)
            if done is not None:
                done.set()
        self._apply_tier_exports()

    def _apply_tier_exports(self) -> None:
        """Service queued prefill-source export requests
        (``engine.export_cached``): walk the radix index for each asked
        token chain and lift the longest cached prefix to host as a
        shippable payload. Runs on the scheduler thread only — the
        lookup references stay held across the block extraction so
        pressure eviction cannot free the blocks mid-export, then every
        reference is surrendered (export copies bytes, it never adopts
        blocks). Any failure resolves the caller's latch with a miss —
        the asking pod re-prefills, never sees an error."""
        while self._tier_exports:
            try:
                ids_t, box, done = self._tier_exports.popleft()
            except IndexError:
                return
            try:
                payload = self._export_cached_now(list(ids_t))
            except Exception as exc:  # noqa: BLE001 — an export failure is a source miss, never a scheduler crash
                payload = None
                if self._logger is not None:
                    self._logger.warnf(
                        "tier-source export failed (%s: %s); answering "
                        "miss", type(exc).__name__, exc,
                    )
            if payload is not None:
                box.append(payload)
            done.set()

    def _export_cached_now(self, ids: "list[int]") -> Any:
        """The scheduler-thread half of ``export_cached``: radix lookup
        (references held), host-bounce the matched whole blocks, then
        surrender every lookup reference. None on a miss."""
        radix = self._radix
        if radix is None or not self.kv_block:
            return None
        B = self.kv_block
        chain, matched = radix.lookup(ids, 0)
        n = matched // B
        if n <= 0:
            for bid in chain:
                self._allocator.decref(bid)
            return None
        from gofr_tpu.ops.kv_cache import export_blocks

        try:
            return export_blocks(
                self.cache, chain[:n], ids[: n * B], src=self.model_name
            )
        finally:
            # Lookup references surrendered in full: the export shipped
            # COPIES, so the index alone decides how long the source
            # blocks stay cached.
            for bid in chain:
                self._allocator.decref(bid)

    def _import_payload(self, payload: Any) -> int:
        """One payload → pool blocks + radix entries; returns blocks
        actually imported (possibly a prefix of the payload: content
        already cached here is skipped, and a dry pool truncates the
        tail)."""
        radix = self._radix
        if radix is None or not self.kv_block:
            return 0
        if not payload.compatible_with(self.cache) or len(
            payload.token_ids
        ) != payload.n_blocks * payload.block:
            # Re-validated on the applying engine: a supervisor restart
            # between handoff and apply rebuilds the cache, and a
            # payload from a different model/quant geometry must never
            # alias into it. (The byte checksum was already verified at
            # handoff admission; in-proc payload memory cannot rot in
            # between, so only the geometry can go stale here.)
            if self._logger is not None:
                self._logger.warnf(
                    "tier import from %s rejected: stale or corrupt "
                    "payload (%d block(s)); request will re-prefill",
                    payload.src, payload.n_blocks,
                )
            return 0
        B = self.kv_block
        ids = list(payload.token_ids)
        # Chunks already cached here need no copy: walk the longest
        # cached prefix and import only the tail. The lookup references
        # stay HELD until after the insert below — surrendering them
        # first would let _alloc_block's pressure eviction free exactly
        # these nodes mid-import, and insert would then rebuild the
        # chain around stale (reused) block ids.
        chain, matched = radix.lookup(ids, 0)
        start = matched // B
        imported = 0
        from gofr_tpu.ops.kv_cache import DeviceKVPayload

        device_leg = isinstance(payload, DeviceKVPayload)
        for j in range(start, payload.n_blocks):
            bid = self._alloc_block()
            if bid is None:
                break  # pool dry: the un-imported tail re-prefills
            if device_leg:
                try:
                    self._write_block_device_leg(bid, payload, j)
                except Exception as exc:  # noqa: BLE001 — a failed write degrades to re-prefill, never kills the loop
                    # The write runs HERE, on the importing scheduler
                    # thread, after the transfer already returned — a
                    # cross-mesh device_put against a rebuilt mesh (or
                    # any placement failure) must degrade exactly like
                    # a rejected payload: surrender the fresh block,
                    # keep what already imported, and let the tail
                    # re-prefill. Escaping would crash the scheduler
                    # loop over a cache warm.
                    self._allocator.decref(bid)
                    if self._logger is not None:
                        self._logger.warnf(
                            "device-leg block write failed (%s: %s); "
                            "%d/%d block(s) imported, tail will "
                            "re-prefill",
                            type(exc).__name__, exc, imported,
                            payload.n_blocks,
                        )
                    break
            else:
                args = [
                    self.cache,
                    self._up(np.int32(bid)),
                    self._up(payload.k[:, j]),
                    self._up(payload.v[:, j]),
                ]
                if self.cache.k_s is not None and payload.k_s is not None:
                    args += [
                        self._up(payload.k_s[:, j]),
                        self._up(payload.v_s[:, j]),
                    ]
                self.cache = self._paged_insert_block(*args)
            chain.append(bid)
            imported += 1
        n = start + imported
        if n:
            # insert() walks the existing prefix nodes (flag False —
            # the index keeps its own reference, OURS is surrendered
            # below) and ADOPTS the fresh tail blocks' references.
            # Nothing mutates the trie between the lookup above and
            # this insert — both run on the scheduler thread, and
            # purge_aid only ever targets LoRA slots, never aid 0.
            flags = radix.insert(ids[: n * B], chain[:n], 0)
            for j, adopted in enumerate(flags):
                if not adopted:
                    # j < start: drop the reference lookup handed us.
                    # j >= start (duplicate raced in): drop our fresh
                    # block — the incumbent wins.
                    self._allocator.decref(chain[j])
            self._publish_prefix_gauge()
        if self._metrics is not None:
            self._metrics.set_gauge(
                "app_tpu_kv_blocks_free", self._allocator.n_free,
                "model", self.model_name,
            )
        if self._logger is not None:
            self._logger.debugf(
                "tier import from %s: %d/%d block(s) imported (%d "
                "already cached)",
                payload.src, imported, payload.n_blocks, start,
            )
        return imported

    def _write_block_device_leg(self, bid: int, payload: Any, j: int) -> None:
        """Device-leg import of ONE shipped block: place the inbound
        device planes onto this pool's sharding (an explicit
        ``device_put`` — shard-to-shard over ICI/DMA when the meshes
        differ, a no-op when the exporting engine shares them) and
        write them in with the donated fixed-shape ``paged_move_block``.
        Never touches host memory — graftlint GL018 pins that (no
        ``device_get``/``np.asarray`` of cache planes in
        ``*_device_leg``/``paged_move*`` code)."""
        jax = self._jax
        k_blk = payload.k_blocks[j]
        v_blk = payload.v_blocks[j]
        if self._block_sharding is not None:
            k_blk = jax.device_put(k_blk, self._block_sharding)
            v_blk = jax.device_put(v_blk, self._block_sharding)
        args = [self.cache, self._up(np.int32(bid)), k_blk, v_blk]
        if self.cache.k_s is not None and payload.k_s_blocks is not None:
            k_s_blk = payload.k_s_blocks[j]
            v_s_blk = payload.v_s_blocks[j]
            if self._block_sharding is not None:
                k_s_blk = jax.device_put(k_s_blk, self._block_sharding)
                v_s_blk = jax.device_put(v_s_blk, self._block_sharding)
            args += [k_s_blk, v_s_blk]
        self.cache = self._paged_move_block(*args)

    def _export_payload_device_leg(
        self, block_ids: "list[int]", token_ids: "list[int]"
    ) -> Any:
        """Device-leg extraction: lift each finished block's planes out
        of this pool as fresh DEVICE arrays (one fixed-shape jitted
        gather per block — one compile per cache geometry, GSPMD-aware
        so a tp-sharded pool extracts shard-local slices) and wrap them
        with the same content keys / geometry fingerprint the
        host-bounce payload carries. The planes never visit host memory
        (GL018); everything host-side — keys, fingerprint, radix
        bookkeeping — is identical to the host leg."""
        from gofr_tpu.ops.kv_cache import DeviceKVPayload, cache_geometry

        ks: "list[Any]" = []
        vs: "list[Any]" = []
        kss: "list[Any]" = []
        vss: "list[Any]" = []
        for bid in block_ids:
            k_blk, v_blk, k_s_blk, v_s_blk = self._paged_extract_block(
                self.cache, self._up(np.int32(bid))
            )
            ks.append(k_blk)
            vs.append(v_blk)
            if k_s_blk is not None:
                kss.append(k_s_blk)
                vss.append(v_s_blk)
        return DeviceKVPayload(
            block=self.kv_block,
            token_ids=tuple(int(t) for t in token_ids),
            k_blocks=tuple(ks),
            v_blocks=tuple(vs),
            k_s_blocks=tuple(kss) if kss else None,
            v_s_blocks=tuple(vss) if vss else None,
            src=self.model_name,
            geometry=cache_geometry(self.cache),
        )

    def _export_prefilled(self, slot: int, req: _GenRequest) -> bool:
        """Prefill-tier export: offer a just-finalized prefill to the
        pool's transfer exporter instead of decoding locally. True →
        the pool placed the request on a decode replica; the slot's
        blocks are indexed into the LOCAL radix (the next request with
        this prefix aliases instead of re-prefilling) and released.
        False → the caller decodes locally, the fused fallback — a
        collapsed decode tier degrades to today's serving, never drops
        a request. Probe requests (``pin_replica``) and LoRA requests
        always decode locally (a probe must measure THIS replica;
        adapter weights live per-engine)."""
        if (
            self.tier_role != "prefill"
            or self._tier_exporter is None
            or req.pin_replica
            or req.prefix_store
            or req.aid
            # Requests carrying already-delivered tokens (failover
            # continuations that landed here) decode locally: tier
            # export ships FRESH prefills.
            or req.token_ids
        ):
            return False

        def make_payload(leg: str = "host") -> Any:
            # Called by the pool AFTER its cheap gates (hop cap, tier
            # mode, deadline) with the transfer leg it selected: the
            # extraction is the expensive part, and a collapsed decode
            # tier must not pay it per request. Runs synchronously on
            # this thread while the slot's blocks are still held.
            # ``leg="device"`` extracts device-resident block planes
            # (zero host copies); anything else is the deliberate host
            # bounce the wire and host legs ship.
            if not self.kv_block:
                return None
            B = self.kv_block
            row = self._slot_blocks[slot]
            n_full = min(len(req.prompt_ids) // B, len(row))
            if n_full <= 0:
                return None
            if leg == "device":
                return self._export_payload_device_leg(
                    row[:n_full], req.prompt_ids[: n_full * B]
                )
            from gofr_tpu.ops.kv_cache import export_blocks

            return export_blocks(
                self.cache, row[:n_full],
                req.prompt_ids[: n_full * B],
                src=self.model_name,
            )

        try:
            # Fault seam: the prefill replica failing at the prefill→
            # transfer boundary (extraction crash, device loss right
            # after finalize).
            faults.fire("tier.prefill_done", engine=self, request=req)
            placed = bool(self._tier_exporter(req, make_payload))
        except Exception as exc:  # noqa: BLE001 — every export failure has a local fallback
            if self._logger is not None:
                self._logger.errorf(
                    "tier export failed (%s: %s); decoding locally",
                    type(exc).__name__, exc,
                )
            placed = False
        if not placed:
            return False
        if self.kv_block:
            # Warm the local radix with the full prompt blocks before
            # releasing the slot (reads only immutable request fields —
            # the decode replica owns the mutable ones by now), so the
            # prefill tier's repeated-prefix traffic aliases instead of
            # re-prefilling.
            adopted: set[int] = set()
            if self._radix is not None:
                row = self._slot_blocks[slot]
                n_full = min(len(req.prompt_ids) // self.kv_block, len(row))
                if n_full > 0:
                    flags = self._radix.insert(
                        req.prompt_ids, row[:n_full], 0
                    )
                    adopted = {
                        row[j] for j, f in enumerate(flags) if f
                    }
            self._release_blocks(slot, adopted)
            if self._metrics is not None:
                self._metrics.set_gauge(
                    "app_tpu_kv_blocks_free", self._allocator.n_free,
                    "model", self.model_name,
                )
                self._publish_prefix_gauge()
        return True

    def _radix_watermark_sweep(self) -> None:
        """Proactive prefix-cache eviction (``TPU_PREFIX_EVICT_WM``):
        keep at least the watermark's worth of pool blocks FREE by
        sweeping LRU radix entries once per loop iteration, so
        admission under pressure finds free blocks waiting instead of
        paying a synchronous pre-evict scan inside its own grow. 0
        (default) = off: eviction happens only on allocation shortfall,
        exactly the pre-watermark behavior.

        The EFFECTIVE watermark is resolved at boot: the explicit
        block-count knob when set, else derived from the HBM ledger's
        headroom target (``TPU_PREFIX_EVICT_HBM_FRAC`` — keep
        frac×budget of device HBM free, converted to blocks via the
        pool's bytes-per-block)."""
        wm = self.effective_evict_watermark
        if not wm or self._radix is None:
            return
        short = wm - self._allocator.n_free
        if short <= 0:
            return
        # Fruitless-sweep latch: when nothing was evictable (every
        # cached leaf still aliased by live slots), re-scanning the
        # whole trie every loop iteration is pure hot-path overhead —
        # skip until the free count or the cache composition changes.
        sig = (self._allocator.n_free, self._radix.n_cached_blocks)
        if sig == self._wm_fruitless:
            return
        if self._radix.evict(short):
            self._wm_fruitless = None
            self._publish_prefix_gauge()
            if self._metrics is not None:
                self._metrics.set_gauge(
                    "app_tpu_kv_blocks_free", self._allocator.n_free,
                    "model", self.model_name,
                )
        else:
            self._wm_fruitless = sig

    def _window_tokens(self) -> int:
        return self.window_k * (self.spec_tokens + 1)

    def _dispatch_prefill_chunk(self, lap_import: bool = False) -> bool:
        """Admit pending requests into free slots and dispatch ONE
        fixed-shape [prefill_batch, prefill_chunk] chunk step.
        ``lap_import`` is True only on the scheduler pass's first
        (seam) call: the loop profiler's tier_import stamp belongs to
        that one — see the lap site below.

        Each row advances one slot's prompt by up to ``prefill_chunk``
        tokens; rows whose prompt completes sample their first token and
        merge it into the decode token vector ON DEVICE (no host roundtrip
        between prefill and decode). Returns True if a step was dispatched.
        """
        # Disaggregated-tier imports (shipped KV blocks → radix index)
        # apply HERE, immediately ahead of the admission pops, so a
        # just-transferred request's alias walk hits its own shipped
        # blocks instead of re-prefilling them (a payload landing after
        # its request was popped still applies next call — the request
        # just pays a redundant prefill, never a wrong answer).
        if self.kv_block:
            self._apply_tier_imports()
            if lap_import and self._loop_prof is not None:
                # Tier-import apply is its own loop phase: shipped-block
                # writes are device work that would otherwise hide
                # inside "prefill" (one stamp per apply, not per block).
                # Only the PASS-SEAM call laps — re-entries from the
                # wave-admission loop or _process_window's mega-mode
                # readiness poll would otherwise attribute prefill work
                # (or the device-window wait itself) to tier_import and
                # invert the host-overhead diagnosis.
                self._loop_prof.lap("tier_import", self._obs.now())
        # Admission is host bookkeeping only — the device work is the
        # chunk steps that follow.
        free = [
            i for i, s in enumerate(self._slots)
            if s is None and i not in self._prefilling
        ]
        while free and (self._wait_kv or not self._pending.empty()):
            if self._wait_kv:
                req = self._wait_kv.popleft()
            else:
                try:
                    req = self._pending.get_nowait()
                except queue.Empty:
                    break
                self._note_dequeued(req)
            # Admission-time lifecycle check: a request that was
            # cancelled or whose deadline expired while queued must not
            # occupy a KV slot at all.
            if self._reap_request(req):
                continue
            if req.aid and req.lora_gen != self._lora_gen[req.aid]:
                # The adapter slot was reloaded/unloaded while this
                # request sat in the queue — its stamp no longer matches,
                # so admitting it would run under weights the caller
                # never asked for. Prefix registrations resolve -1 (their
                # documented stale-store outcome); generate requests fail
                # loudly.
                if not req.future.done():
                    if req.prefix_store:
                        req.future.set_result(-1)
                    else:
                        req.future.set_exception(RuntimeError(
                            f"LoRA adapter slot {req.aid} was reloaded or "
                            "unloaded while this request was queued; "
                            "resubmit against the current adapter set"
                        ))
                req.stream.put(None)
                self._obs_finish(req, "error", "lora_reloaded")
                continue
            # Replay-aware admission: a request the supervisor carried
            # across a restart re-prefills prompt + already-delivered
            # tokens (prefill_ids), so decode resumes at exactly the
            # next token. Fresh requests: prefill_ids IS the prompt.
            pids = req.prefill_ids()
            # Clamp generation budget so pipelined-window overshoot can't
            # overrun the cache (admission-time guard; see
            # _dispatch_window). Done BEFORE any block allocation so the
            # replay-complete early-retire below cannot strand pool
            # blocks on a slot it never occupies.
            room = (
                self.max_len - 1 - len(req.prompt_ids)
                - (self.pipeline_depth + 1) * self.window_k
                * (self.spec_tokens + 1)
            )
            req.max_new_tokens = max(1, min(req.max_new_tokens, room))
            if req.replayed_tokens >= req.max_new_tokens:
                # The clamp (or the original budget) is already covered
                # by the tokens delivered before the restart: the request
                # is complete — retire it with the full result instead of
                # prefilling a slot to generate nothing.
                seq = _ActiveSeq(request=req, last_token=req.token_ids[-1])
                self._retire(-1, seq)
                continue
            cached_done = 0
            if self.kv_block:
                # A request bigger than the ENTIRE pool can never be
                # admitted — fail it now instead of deadlocking the
                # admission queue behind it forever.
                B = self.kv_block
                need = (min(len(pids) + 1, self.max_len) + B - 1) // B
                if need > self.cache.n_blocks - 1:
                    if not req.future.done():
                        req.future.set_exception(RuntimeError(
                            f"prompt needs {need} KV blocks but the pool "
                            f"has {self.cache.n_blocks - 1}; raise "
                            f"TPU_KV_POOL_BLOCKS"
                        ))
                    req.stream.put(None)
                    self._obs_finish(req, "error", "kv_pool_too_small")
                    continue
                # Automatic prefix cache (TPU_AUTO_PREFIX): alias the
                # longest cached full-block prefix into the slot's table
                # — zero-copy — and chunk-prefill only the remainder.
                cached_done = self._alias_prefix_blocks(free[0], req, pids)
                # Cover the prompt + the first decode token now; windows
                # top up ahead of dispatch. Pool dry → hold the request
                # back (retirements will refill the free list), dropping
                # any aliased references so cached blocks never strand
                # on a slot the request does not occupy.
                if not self._ensure_blocks(
                    free[0], len(pids) + 1
                ):
                    # Unconditional: aliasing may have seeded the row
                    # (even a COW'd block on a zero-length hit), and a
                    # deferred request must leave the slot's row empty.
                    self._release_blocks(free[0])
                    self._wait_kv.appendleft(req)
                    break
                self._dispatched_tokens[free[0]] = 0
            slot = free.pop(0)
            self._seeds_host[slot] = req.seed
            # Sampling-counter offset: a replayed request resumes its
            # counter-based sample path at the delivered-token count
            # (fresh requests start at 0), so non-greedy streams carried
            # across a restart continue byte-identically.
            self._noff_host[slot] = req.replayed_tokens
            self._aids_host[slot] = req.aid
            self._bidx_host[slot, :] = -1
            self._bval_host[slot, :] = 0.0
            for j, (tok, bv) in enumerate(req.logit_bias.items()):
                self._bidx_host[slot, j] = tok
                self._bval_host[slot, j] = bv
            self._seeds_dirty = True
            state = _PrefillState(request=req, ids=pids)
            if cached_done:
                # Aliased blocks already hold these positions' K/V;
                # done < len(pids) always (the clamp in
                # _alias_prefix_blocks), so the finalize chunk still
                # runs and samples the first token — re-writing the
                # boundary position lands in a COW'd or fresh block.
                state.done = cached_done
            if self._prefix_pool is not None and not req.prefix_store:
                # Per-adapter pools: pooled K/V is a function of the
                # weights that prefilled it, so a request only reuses a
                # prefix registered under its OWN adapter.
                idx, plen = self._prefix_pool.lookup(pids, req.aid)
                if idx >= 0:
                    # Copy pooled KV rows in; prefill only the remainder.
                    # done < len(prompt) always, so the final chunk still
                    # runs and samples the first token (re-writing the
                    # boundary token's K/V is idempotent).
                    self.cache = self._prefix_pool.load(
                        self.cache, idx, slot, plen
                    )
                    state.done = min(plen, len(pids) - 1)
                    if self._metrics is not None:
                        self._metrics.increment_counter(
                            "app_tpu_prefix_hits", "model", self.model_name
                        )
            self._prefilling[slot] = state
            if req.aid and req.lora_gen != self._lora_gen[req.aid]:
                # load/unload_lora raced this admission: the generation
                # bump landed after the queue-pop staleness check above,
                # and its in-flight failure snapshot may have run before
                # this request became visible in _prefilling. Now that
                # it IS visible, one of the two sides must catch it —
                # re-validate here so aliased blocks holding the OLD
                # weights' K/V are surrendered instead of decoded
                # against, failing the request exactly like the
                # queue-pop path.
                del self._prefilling[slot]
                if self.kv_block:
                    self._release_blocks(slot)
                free.insert(0, slot)
                if not req.future.done():
                    if req.prefix_store:
                        req.future.set_result(-1)
                    else:
                        req.future.set_exception(RuntimeError(
                            f"LoRA adapter slot {req.aid} was reloaded "
                            "or unloaded while this request was being "
                            "admitted; resubmit against the current "
                            "adapter set"
                        ))
                req.stream.put(None)
                self._obs_finish(req, "error", "lora_reloaded")
                continue
            # Observability: admission is now CERTAIN (every reject path
            # above `continue`d) — stamp the queue-wait end. One clock
            # read per admitted request, admission-rate not token-rate,
            # shared by the timeline and the tenant ledger.
            tl = req.timeline
            led = self._tenant_ledger
            if tl is not None or led is not None:
                now_adm = self._obs.now()
                if tl is not None:
                    tl.mark_admitted(now_adm)
                if led is not None:
                    led.note_admitted(req, now_adm)
            if cached_done:
                # Count hit tokens only once admission is CERTAIN —
                # a pool-dry deferral re-runs the alias walk on
                # re-admission (double-counting the same hit), and the
                # staleness re-check above can still reject outright.
                self._prefix_hit_tokens += cached_done
                if tl is not None:
                    tl.note_prefix_hit(cached_done)
                if self._metrics is not None:
                    self._metrics.add_counter(
                        "app_tpu_prefix_hit_tokens_total", cached_done,
                        "model", self.model_name,
                    )
        if not self._prefilling:
            return False
        # Fault seam: a raise here is a device failure at prefill
        # dispatch — the scheduler's death drain must fail every caller.
        faults.fire("scheduler.device_step", engine=self, kind="prefill")
        self._check_superseded()
        # Host-side dispatch count (exactly one chunk step — multi OR
        # single — leaves this method per True return): the prefix-cache
        # tests assert a warm request takes strictly fewer steps.
        self._prefill_chunk_steps += 1
        if self._seeds_dirty:
            # Upload the admission-scoped planes BEFORE any dispatch —
            # the deep multi-chunk branch below reads _aids_dev, so a
            # flush only on the single-chunk path would prefill a long
            # prompt with the slot's PREVIOUS occupant's adapter.
            self._seeds_dev = self._up(self._seeds_host)
            self._noff_dev = self._up(self._noff_host)
            self._bidx_dev = self._up(self._bidx_host)
            self._bval_dev = self._up(self._bval_host)
            self._aids_dev = self._up(self._aids_host)
            self._seeds_dirty = False

        P, c = self.prefill_batch, self.prefill_chunk
        rows = list(self._prefilling.items())[:P]

        # Multi-chunk fast path: rows with ≥2 full chunks before their
        # finalize chunk burn through up to prefill_depth of them in one
        # device-side loop (no sampling, no finalize — the single-chunk
        # step below always closes a prompt). Only DEEP rows join the
        # batch — one short prompt admitted alongside an 8k one must not
        # disable the amortizer for the long row; shallow rows take the
        # single-chunk step next loop iteration. Paged mode needs no
        # per-chunk allocation: admission already covered the whole prompt.
        if self.prefill_depth > 1:
            deep = [
                (slot, st, rem)
                for slot, st in rows
                for rem in [(len(st.ids) - st.done - 1) // c]
                if rem >= 2
            ]
            if deep:
                d = min(min(rem for _, _, rem in deep), self.prefill_depth)
            if deep and d >= 2:
                D = self.prefill_depth
                tokens3 = np.zeros((D, P, c), dtype=np.int32)
                slots_m = np.zeros((P,), dtype=np.int32)
                starts_m = np.zeros((P,), dtype=np.int32)
                for i, (slot, st, _) in enumerate(deep):
                    ids = st.ids
                    for j in range(d):
                        lo = st.done + j * c
                        tokens3[j, i, :] = ids[lo : lo + c]
                    slots_m[i] = slot
                    starts_m[i] = st.done
                for i in range(len(deep), P):  # pad rows duplicate row 0
                    tokens3[:, i, :] = tokens3[:, 0, :]
                    slots_m[i], starts_m[i] = slots_m[0], starts_m[0]
                t0 = time.time()
                t0m = self._obs.now()
                self._push_table()
                margs = (
                    self.params, self.cache, self._up(tokens3),
                    self._up(slots_m), self._up(starts_m),
                    self._up(np.int32(d)),
                )
                # Locals-then-commit around the dispatch (same zombie
                # fence as _dispatch_window): a wedged call that returns
                # after abandonment must not clobber the new cache.
                mhist = None
                if self.spec_tokens:
                    mcache, mhist = self._prefill_multi_chunk_hist(
                        *margs, self._history_dev, self._aids_dev
                    )
                else:
                    mcache = self._prefill_multi_chunk(
                        *margs, self._aids_dev
                    )
                self._check_superseded()
                self.cache = mcache
                if mhist is not None:
                    self._history_dev = mhist
                if self._lockstep:
                    self._jax.block_until_ready(self.cache.lengths)  # graftlint: disable=GL019 — multi-process CPU lockstep barrier (gloo collective ordering), a deliberate device wait
                # One clock read per multi-chunk DISPATCH, shared by
                # every row it advanced (timestamps at window
                # granularity — graftlint GL011).
                t1m = self._obs.now()
                for _, st, _ in deep:
                    st.done += d * c
                    if st.request.timeline is not None:
                        st.request.timeline.note_chunk(t0m, t1m, d * c)
                if self._metrics is not None:
                    self._metrics.record_histogram(
                        "app_tpu_infer_latency", time.time() - t0,
                        "kind", "prefill_multi",
                    )
                return True

        tokens = np.zeros((P, c), dtype=np.int32)
        slots = np.zeros((P,), dtype=np.int32)
        starts = np.zeros((P,), dtype=np.int32)
        lens = np.zeros((P,), dtype=np.int32)
        finalize = np.zeros((P,), dtype=bool)
        row_valid = np.zeros((P,), dtype=bool)
        temps = np.ones((P,), dtype=np.float32)
        topps = np.ones((P,), dtype=np.float32)
        greedy = np.ones((P,), dtype=bool)
        for i, (slot, st) in enumerate(rows):
            ids = st.ids
            chunk = ids[st.done : st.done + c]
            tokens[i, : len(chunk)] = chunk
            slots[i] = slot
            starts[i] = st.done
            lens[i] = len(chunk)
            finalize[i] = st.done + len(chunk) >= len(ids)
            row_valid[i] = True
            temps[i] = max(st.request.temperature, 0.0)
            topps[i] = st.request.top_p
            greedy[i] = st.request.temperature <= 0
        for i in range(len(rows), P):
            # Padding rows duplicate row 0: identical K/V writes to the
            # same cache positions are idempotent, and row_valid=False
            # keeps them out of the finalize merge.
            tokens[i] = tokens[0]
            slots[i], starts[i], lens[i] = slots[0], starts[0], lens[0]
            temps[i], greedy[i], topps[i] = temps[0], greedy[0], topps[0]

        jnp = self._jnp
        t0 = time.time()
        t0m = self._obs.now()
        self._push_table()
        args = (
            self.params, self.cache, self._up(tokens),
            self._up(slots), self._up(starts), self._up(lens),
            self._up(finalize), self._up(row_valid),
            self._up(temps), self._up(greedy), self._up(topps),
            self._seeds_dev, self._tokens_dev, self._logps_dev,
            self._pcounts_dev, self._nsteps_dev, self._bidx_dev,
            self._bval_dev, self._topi_dev, self._topl_dev,
            self._aids_dev, self._noff_dev,
        )
        # Static compile choice: the no-bias program has no bias scatter
        # at all (each variant compiles once, then caches).
        use_bias = any(
            st.request.logit_bias for _, st in rows
        )
        # Locals-then-commit around the dispatch (zombie fence; see
        # _dispatch_window).
        chist = None
        if self.spec_tokens:
            (ccache, ctoks, clps, first_dev,
             first_lp_dev, cpc, cnst,
             cti, ctl, ftopi_dev, ftopl_dev, chist) = (
                self._prefill_chunk_step_hist(
                    *args, self._history_dev, use_bias=use_bias
                )
            )
        else:
            (ccache, ctoks, clps, first_dev,
             first_lp_dev, cpc, cnst,
             cti, ctl, ftopi_dev, ftopl_dev) = (
                self._prefill_chunk_step(*args, use_bias=use_bias)
            )
        self._check_superseded()
        self.cache, self._tokens_dev, self._logps_dev = ccache, ctoks, clps
        self._pcounts_dev, self._nsteps_dev = cpc, cnst
        self._topi_dev, self._topl_dev = cti, ctl
        if chist is not None:
            self._history_dev = chist
        if self._lockstep:
            self._jax.block_until_ready(first_dev)  # graftlint: disable=GL019 — multi-process CPU lockstep barrier (gloo collective ordering), a deliberate device wait
        if self._metrics is not None:
            self._metrics.record_histogram(
                "app_tpu_infer_latency", time.time() - t0, "kind", "prefill"
            )
            self._metrics.record_histogram(
                "app_tpu_batch_size", len(rows), "batcher", "prefill"
            )

        emits_started = False
        # One clock read per chunk DISPATCH (window granularity); the
        # per-row loop below only copies it into timelines.
        t1m = self._obs.now()
        for i, (slot, st) in enumerate(rows):
            st.done += int(lens[i])
            tl = st.request.timeline
            if tl is not None:
                tl.note_chunk(t0m, t1m, int(lens[i]))
            if finalize[i]:
                if tl is not None:
                    tl.mark_prefill_done(t1m)
                st.request.effective_prompt_len = st.done
                del self._prefilling[slot]
                if st.request.prefix_store:
                    # Park the rows in the pool instead of decoding; the
                    # slot goes straight back to the free list. A prefix
                    # whose adapter was reloaded/unloaded while this
                    # prefill was in flight prefilled under the WRONG
                    # weights — drop it (resolve -1) instead of
                    # registering stale K/V under a reusable slot id.
                    r_aid = st.request.aid
                    if r_aid and st.request.lora_gen != self._lora_gen[r_aid]:
                        if not st.request.future.done():
                            st.request.future.set_result(-1)
                    else:
                        idx = self._prefix_pool.store(
                            st.request.prompt_ids, self.cache, slot,
                            r_aid,
                        )
                        if not st.request.future.done():
                            st.request.future.set_result(idx)
                    st.request.stream.put(None)
                elif (
                    st.request.aid
                    and st.request.lora_gen
                    != self._lora_gen[st.request.aid]
                ):
                    # Generate request whose adapter slot was reloaded
                    # after admission (the admission stamp check and
                    # load_lora's in-flight snapshot bracket a tiny
                    # check-then-insert window on the scheduler thread;
                    # this finalize-time re-check closes it). It must
                    # not start decoding under weights the caller never
                    # asked for.
                    if not st.request.future.done():
                        st.request.future.set_exception(RuntimeError(
                            f"LoRA adapter slot {st.request.aid} was "
                            "reloaded while this request was prefilling; "
                            "resubmit against the current adapter set"
                        ))
                    st.request.stream.put(None)
                    self._release_slot(slot)
                else:
                    if self._export_prefilled(slot, st.request):
                        # Disaggregated tier: the pool placed this
                        # request's decode phase on a decode replica
                        # (KV blocks shipped or re-prefilling there);
                        # the slot is free again for the next prefill.
                        continue
                    seq = _ActiveSeq(request=st.request, last_token=-1)
                    self._slots[slot] = seq
                    self._slot_state_dirty = True
                    # Early first-token emission: the chunk step SAMPLED this
                    # row's first token on device — fetch it asynchronously
                    # and emit the moment it lands (~prefill + one-way RTT)
                    # instead of after the first decode window drains through
                    # the pipeline (~3 windows ≈ 300 ms on the relay).
                    if not emits_started:
                        emits_started = True
                        fetches = [first_dev, first_lp_dev]
                        if self.top_logprobs:
                            fetches += [ftopi_dev, ftopl_dev]
                        for arr in fetches:
                            try:
                                arr.copy_to_host_async()
                            except AttributeError:
                                pass
                    self._prefill_emits.append(
                        (first_dev, first_lp_dev, ftopi_dev, ftopl_dev, i,
                         slot, seq)
                    )
        self._update_slot_gauges()
        return True

    def _flush_prefill_emits(self) -> None:
        """Emit first tokens whose async prefill fetch has landed.

        Non-blocking (``is_ready`` poll); each entry emits at most once —
        if a decode window's processing got there first (the loaded case),
        the entry is dropped.
        """
        if not self._prefill_emits:
            return
        self._check_superseded()
        # One host materialization per DEVICE ARRAY per flush: entries
        # from the same chunk dispatch share their fetched arrays, and
        # np.asarray inside the per-entry loop re-copied the full array
        # once per emitting row per window. Keyed by id() — the arrays
        # are alive for the duration of this pass (held by `entries`).
        host_cache: dict[int, np.ndarray] = {}

        def pull(arr: Any) -> np.ndarray:
            h = host_cache.get(id(arr))
            if h is None:
                # Landed (is_ready) + started async at dispatch: a copy,
                # not a sync.
                h = np.asarray(arr)  # graftlint: disable=GL001
                host_cache[id(arr)] = h
            return h

        keep = []
        # One timestamp pair per FLUSH, shared by every entry that emits
        # in it (per-row clock reads in this loop were exactly the host
        # overhead graftlint GL011 exists to flag; entries in one flush
        # landed together, so a shared stamp loses nothing).
        now = time.time()
        now_m = self._obs.now()
        for entry in self._prefill_emits:
            first_dev, lp_dev, ftopi_dev, ftopl_dev, row, slot, seq = entry
            req = seq.request
            # The window emission path won the race (token already out),
            # or the request is gone — nothing to do.
            if req.future.done() or req.token_ids or seq.first_emitted:
                continue
            # Cancelled/expired between finalize and this flush: retire
            # NOW instead of emitting a first token to a caller that
            # already gave up (the reap releases the slot too).
            if self._reap_request(
                req, slot=slot if self._slots[slot] is seq else -1
            ):
                continue
            try:
                if not first_dev.is_ready():
                    keep.append(entry)
                    continue
            except AttributeError:  # fake/CPU backends: always ready
                pass
            tok = int(pull(first_dev)[row])
            lp = float(pull(lp_dev)[row])
            top = None
            if self.top_logprobs and req.top_logprobs:
                ti = pull(ftopi_dev)[row]
                tl = pull(ftopl_dev)[row]
                top = [
                    (int(ti[j]), float(tl[j]))
                    for j in range(req.top_logprobs)
                ]
            req.ttft_s = now - req.enqueued_at
            seq.first_token_at = now
            seq.first_emitted = True
            if req.timeline is not None:
                req.timeline.mark_first_token(now_m)
            seq.last_token = tok
            seq.n_generated += 1
            self._emit_token(seq, tok, lp, top)
            if self._finished(seq):
                self._retire(slot, seq)
                if self._slots[slot] is seq:
                    self._release_slot(slot)
        self._prefill_emits = keep

    def _dispatch_window(self) -> tuple:
        """Dispatch one k-step device window (non-blocking) and start the
        async device→host copy of its emitted block — [2, k, S] for plain
        decode, [2, k, S, G+1] plus a [k, S] counts array for speculative
        windows, [2, m*k, S] plus a windows-run scalar for mega windows.
        Returns ``(emitted_dev, counts_dev_or_None, slots_snapshot,
        t_dispatch, wrun_dev_or_None)`` for _process_window — the snapshot
        matters because by processing time a retired slot may already hold
        a NEW request admitted in between."""
        # Fault seam: a raise models the device failing a decode window;
        # an armed action that blocks models a hung step (watchdog).
        faults.fire("scheduler.device_step", engine=self, kind="decode")
        self._check_superseded()
        jnp = self._jnp
        if self._slot_state_dirty:
            # Slot composition changed since the last window: re-upload the
            # [n_slots] state vectors once. Steady-state windows skip this —
            # dispatch is then pure device work, no H2D copies at all.
            active = np.zeros((self.n_slots,), dtype=bool)
            temps = np.ones((self.n_slots,), dtype=np.float32)
            topps = np.ones((self.n_slots,), dtype=np.float32)
            greedy = np.ones((self.n_slots,), dtype=bool)
            fpen = np.zeros((self.n_slots,), dtype=np.float32)
            ppen = np.zeros((self.n_slots,), dtype=np.float32)
            for i, seq in enumerate(self._slots):
                if seq is not None:
                    active[i] = True
                    temps[i] = max(seq.request.temperature, 0.0)
                    topps[i] = seq.request.top_p
                    greedy[i] = seq.request.temperature <= 0
                    fpen[i] = seq.request.frequency_penalty
                    ppen[i] = seq.request.presence_penalty
            self._active_dev = self._up(active)
            self._temps_dev = self._up(temps)
            self._topp_dev = self._up(topps)
            self._greedy_dev = self._up(greedy)
            if self.enable_penalties:
                self._fpen_dev = self._up(fpen)
                self._ppen_dev = self._up(ppen)
            self._slot_state_dirty = False

        # Mega-window mode: compute each slot's remaining budget on the
        # host (it knows tokens_in_flight) and hand it to the device loop;
        # coverage accounting uses the same number so `wants_more` gating
        # stays exact (the device delivers ≥ min(m·k, remaining) steps per
        # slot — early exit only fires once every remaining hits 0 or EOS,
        # and an EOS slot is retired by processing, so accounting can
        # never strand a live slot).
        mega = self.mega_windows
        use_bias = any(
            seq is not None and seq.request.logit_bias
            for seq in self._slots
        )
        remaining_host = eos_stop_host = None
        cover = self.window_k * mega  # guaranteed MINIMUM emissions
        if mega > 1:
            remaining_host = np.zeros((self.n_slots,), dtype=np.int32)
            eos_stop_host = np.zeros((self.n_slots,), dtype=bool)
            for i, seq in enumerate(self._slots):
                if seq is not None:
                    remaining_host[i] = max(
                        0,
                        seq.request.remaining_new_tokens + 1
                        - seq.tokens_in_flight,
                    )
                    eos_stop_host[i] = seq.request.stop_on_eos

        if self.kv_block:
            # Allocation must stay AHEAD of the window about to be
            # dispatched (its writes land before the host sees the
            # tokens). A dry pool mid-stream fails the request — the
            # honest outcome of an oversubscribed pool.
            wt = self._window_tokens()
            for i, seq in enumerate(self._slots):
                if seq is None:
                    continue
                if mega > 1:
                    # Windows this slot still WRITES real K/V for: its
                    # remaining budget covers in ≤ ceil(remaining/k)
                    # windows (spec emits ≥ k/window); each window writes
                    # k*(G+1) positions. Junk past that parks at block 0.
                    k = self.window_k
                    windows_i = min(mega, -(-int(remaining_host[i]) // k))
                    wt = windows_i * k * (self.spec_tokens + 1)
                req = seq.request
                base = req.effective_prompt_len or len(req.prompt_ids)
                need = base + self._dispatched_tokens[i] + wt + 1
                if self._ensure_blocks(i, need):
                    self._dispatched_tokens[i] += wt
                    continue
                if not req.future.done():
                    req.future.set_exception(RuntimeError(
                        "KV block pool exhausted mid-generation "
                        "(raise TPU_KV_POOL_BLOCKS or lower concurrency)"
                    ))
                req.stream.put(None)
                self._obs_finish(req, "error", "kv_pool_exhausted")
                self._release_slot(i)
                if mega > 1:
                    # remaining_host was computed before this loop; the
                    # device must not spin mega windows covering a slot
                    # whose request just failed.
                    remaining_host[i] = 0
                    eos_stop_host[i] = False
            self._push_table()

        for i, seq in enumerate(self._slots):
            if seq is not None:
                seq.tokens_in_flight += (
                    min(cover, int(remaining_host[i])) if mega > 1
                    else self.window_k
                )
        t0 = time.time()
        counts = None
        wrun = None
        etops = None
        # Results land in LOCALS first and commit to self only after a
        # superseded check: a dispatch that BLOCKED here (wedged relay —
        # the exact case the supervisor abandons threads over) must not
        # overwrite the restarted engine's live cache/planes when its
        # stuck call finally returns.
        hist = pc = ti = tl = None
        if mega > 1 and self.spec_tokens:
            (emitted, counts, wrun, toks, lps, cache, nst, hist) = (
                self._mega_spec_window(
                    self.params, self._tokens_dev, self._logps_dev,
                    self.cache, self._active_dev, self._nsteps_dev,
                    self._temps_dev, self._greedy_dev, self._topp_dev,
                    self._history_dev, self._seeds_dev,
                    self._bidx_dev, self._bval_dev,
                    self._up(remaining_host), self._up(eos_stop_host),
                    self._aids_dev,
                    k=self.window_k, m=mega, use_bias=use_bias,
                )
            )
        elif mega > 1:
            (emitted, etops, wrun, toks, lps, cache, nst, pc, ti, tl) = (
                self._mega_window(
                    self.params, self._tokens_dev, self._logps_dev,
                    self.cache, self._active_dev, self._nsteps_dev,
                    self._temps_dev, self._greedy_dev, self._topp_dev,
                    self._fpen_dev, self._ppen_dev, self._pcounts_dev,
                    self._seeds_dev, self._bidx_dev, self._bval_dev,
                    self._topi_dev, self._topl_dev,
                    self._up(remaining_host), self._up(eos_stop_host),
                    self._aids_dev,
                    k=self.window_k, m=mega, use_bias=use_bias,
                )
            )
        elif self.spec_tokens:
            (emitted, counts, toks, lps, cache, nst, hist) = (
                self._spec_window(
                    self.params, self._tokens_dev, self._logps_dev,
                    self.cache, self._active_dev, self._nsteps_dev,
                    self._temps_dev, self._greedy_dev, self._topp_dev,
                    self._history_dev, self._seeds_dev,
                    self._bidx_dev, self._bval_dev, self._aids_dev,
                    k=self.window_k, use_bias=use_bias,
                )
            )
        else:
            (emitted, etops, toks, lps, cache, nst, pc, ti, tl) = (
                self._decode_window(
                    self.params, self._tokens_dev, self._logps_dev,
                    self.cache, self._active_dev, self._nsteps_dev,
                    self._temps_dev, self._greedy_dev, self._topp_dev,
                    self._fpen_dev, self._ppen_dev, self._pcounts_dev,
                    self._seeds_dev, self._bidx_dev, self._bval_dev,
                    self._topi_dev, self._topl_dev, self._aids_dev,
                    k=self.window_k, use_bias=use_bias,
                )
            )
        self._check_superseded()
        self._tokens_dev, self._logps_dev = toks, lps
        self.cache, self._nsteps_dev = cache, nst
        if hist is not None:
            self._history_dev = hist
        if pc is not None:
            self._pcounts_dev, self._topi_dev, self._topl_dev = pc, ti, tl
        if etops is not None and not any(
            seq is not None and seq.request.top_logprobs
            for seq in self._slots
        ):
            # Nobody asked for alternatives: skip the [2, m*k, S, K]
            # device→host block entirely (the program computes it either
            # way; the fetch is what costs on the dispatch path).
            etops = None
        extras = [a for a in (counts, wrun, etops) if a is not None]
        for arr in (emitted, *extras):
            try:
                arr.copy_to_host_async()
            except AttributeError:  # older jax / fake backends
                pass
        if self._lockstep:
            lockcheck.note_device_sync("lockstep_block_until_ready")
            self._jax.block_until_ready(emitted)
        return emitted, counts, list(self._slots), t0, wrun, etops

    def _process_window(
        self,
        emitted: Any,
        counts: Any,
        snapshot: "list[Optional[_ActiveSeq]]",
        t0: float,
        wrun: Any = None,
        etops: Any = None,
    ) -> None:
        t_fetch = time.time()
        # Interruptible wait: while this window's block is in flight, flush
        # any prefill first-token fetches that land first (unloaded TTFT
        # would otherwise be gated on the window fetch). Mega mode also
        # keeps ADMITTING during the wait — prefill chunks for queued
        # requests ride the device queue behind the in-flight mega window,
        # overlapping next-wave admission with current-wave decode.
        if (self._prefill_emits or wrun is not None) and hasattr(
            emitted, "is_ready"
        ):
            while not emitted.is_ready():
                if wrun is not None:
                    self._dispatch_prefill_chunk()
                self._flush_prefill_emits()
                # Device-readiness poll: there is no host-side event to
                # wait on for an in-flight device computation, and the
                # 1 ms granularity is what lets prefill emits interleave
                # with the window fetch. Not a latency-adding sleep.
                time.sleep(0.001)  # graftlint: disable=GL004
        # Decode: [2, k, S] (mega: [2, m*k, S], first wrun*k valid).
        # Spec: [2, k, S, G+1] + counts [k, S].
        lockcheck.note_device_sync("decode_window_fetch")
        emitted_host = np.asarray(emitted)
        # The fetch above is this loop's other blocking point (a wedged
        # relay stalls HERE, not only at dispatch): if the supervisor
        # abandoned this thread while it was stuck, the token block in
        # hand belongs to the OLD engine — emitting it would duplicate
        # tokens on replayed streams and release slots/blocks of the
        # restarted scheduler's allocator.
        self._check_superseded()
        counts_host = np.asarray(counts) if counts is not None else None
        etops_host = np.asarray(etops) if etops is not None else None
        steps = (
            self.window_k if wrun is None
            else int(np.asarray(wrun)) * self.window_k
        )
        if self._metrics is not None:
            # decode_fetch = host-blocking time (what pipelining hides);
            # decode_window_pipeline = dispatch→processed incl. D windows
            # of pipeline queueing (NOT per-window device latency).
            now_m = time.time()
            self._metrics.record_histogram(
                "app_tpu_infer_latency", now_m - t_fetch, "kind", "decode_fetch"
            )
            self._metrics.record_histogram(
                "app_tpu_infer_latency", now_m - t0,
                "kind", "decode_window_pipeline",
            )

        now = time.time()
        mono_now = self._obs.now()  # shared by every row in this window
        emitted_n = 0  # client-visible emissions this window (gauge)
        for i, seq in enumerate(snapshot):
            if seq is None:
                continue
            if seq.request.future.done():
                # Retired by an earlier window's processing (overshoot
                # tokens — drop), or cancelled by the caller mid-flight:
                # free the slot or it would stay active forever.
                if self._slots[i] is seq:
                    seq.request.stream.put(None)
                    # Overshoot after a normal retirement is already
                    # summarized (the timeline latch makes this a
                    # no-op); a caller-cancelled live generation gets
                    # its terminal record here.
                    self._obs_finish(
                        seq.request,
                        "cancelled" if seq.request.future.cancelled()
                        else "ok",
                    )
                    self._release_slot(i)
                    # A future in CANCELLED state (not resolved) means the
                    # caller abandoned a live generation — count it here
                    # because this release races the lifecycle reap and
                    # whichever runs first frees the slot. (cancel() on a
                    # completed future is a no-op, so normal retirements
                    # whose token trips afterwards never miscount.)
                    if (
                        seq.request.future.cancelled()
                        and self._metrics is not None
                    ):
                        self._metrics.increment_counter(
                            "app_tpu_requests_cancelled_total",
                            "model", self.model_name,
                        )
                continue
            if seq.request.ttft_s == 0.0:
                seq.request.ttft_s = now - seq.request.enqueued_at
                seq.first_token_at = now
                if seq.request.timeline is not None:
                    seq.request.timeline.mark_first_token(mono_now)
            if counts_host is None:
                step_toks = (
                    ((emitted_host[0, step, i], emitted_host[1, step, i]),)
                    for step in range(steps)
                )  # enumerate() below recovers the step index for etops
            else:
                step_toks = (
                    tuple(
                        (emitted_host[0, step, i, j], emitted_host[1, step, i, j])
                        for j in range(int(counts_host[step, i]))
                    )
                    for step in range(steps)
                )
            want_top = (
                etops_host is not None and seq.request.top_logprobs
            )
            done = False
            for step, toks in enumerate(step_toks):
                for tok_f, lp in toks:
                    if seq.first_emitted and not seq.first_skip_done:
                        # This position repeats the prefill-sampled token
                        # that _flush_prefill_emits already emitted.
                        seq.first_skip_done = True
                        continue
                    tok = int(tok_f)
                    top = None
                    if want_top:
                        top = [
                            (int(etops_host[0, step, i, j]),
                             float(etops_host[1, step, i, j]))
                            for j in range(seq.request.top_logprobs)
                        ]
                    seq.last_token = tok
                    seq.n_generated += 1
                    emitted_n += 1
                    self._emit_token(seq, tok, float(lp), top)
                    if self._finished(seq):
                        self._retire(i, seq)
                        if self._slots[i] is seq:
                            self._release_slot(i)
                        done = True
                        break
                if done:
                    break
        if counts_host is not None and self._metrics is not None:
            # Acceptance observability: tokens-per-live-step across the
            # window (1.0 = no draft accepted, spec_tokens+1 = all).
            live = counts_host > 0
            if live.any():
                self._metrics.record_histogram(
                    "app_tpu_spec_tokens_per_step",
                    float(counts_host[live].mean()),
                    "model", self.model_name,
                )
        if self._metrics is not None and steps:
            # Per-WINDOW observability gauges (one set_gauge each per
            # processed window, from host values already in hand — no
            # per-token work, no device pulls): how full the batch is,
            # how long a decode step takes (dispatch→processed over the
            # window's steps — includes the pipeline's D windows of
            # queueing, i.e. the number real tokens actually wait), and
            # how many client-visible tokens a step yields.
            in_use = sum(1 for s in self._slots if s is not None)
            self._metrics.set_gauge(
                "app_tpu_batch_occupancy",
                in_use / max(1, self.n_slots),
                "model", self.model_name,
            )
            self._metrics.set_gauge(
                "app_tpu_decode_step_seconds", (now - t0) / steps,
                "model", self.model_name,
            )
            self._metrics.set_gauge(
                "app_tpu_tokens_per_step", emitted_n / steps,
                "model", self.model_name,
            )
        self._update_slot_gauges()

    def _emit_token(
        self,
        seq: _ActiveSeq,
        tok: int,
        logprob: float,
        top: "Optional[list[tuple[int, float]]]" = None,
    ) -> None:
        req = seq.request
        if req.replay_skip > 0:
            # Exact-replay regeneration phase: this token was already
            # delivered to the client before the restart — swallow the
            # re-generated copy instead of duplicating it on the
            # stream. The walk is deterministic (counter-based
            # sampling), so a mismatch means the replay landed on a
            # different engine seed/params — log it, the stream stays
            # consistent with what was already delivered.
            idx = len(req.token_ids) - req.replay_skip
            if (
                self._logger is not None
                and 0 <= idx < len(req.token_ids)
                and req.token_ids[idx] != tok
            ):
                self._logger.warnf(
                    "exact replay diverged at position %d (%d != %d); "
                    "do the pool's replicas share TPU_SEED?",
                    idx, tok, req.token_ids[idx],
                )
            req.replay_skip -= 1
            return
        if seq.request.top_logprobs:
            seq.request.token_top_logprobs.append(top)
        seq.request.token_ids.append(tok)
        seq.request.token_logprobs.append(logprob)
        seq.request.stream.put(tok)
        # Aggregate-throughput sample feeding projected-wait shedding
        # (engine._throughput_tps): every emission across every slot
        # counts, so the estimate is the batch's rate, not one stream's.
        self._tput.note(1)
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_tokens_generated", "model", self.model_name
            )

    def _finished(self, seq: _ActiveSeq) -> bool:
        req = seq.request
        eos = self.tokenizer.eos_id if self.tokenizer is not None else -1
        if req.stop_on_eos and req.token_ids and req.token_ids[-1] == eos:
            return True
        if req.stop_texts and self.tokenizer is not None:
            text = self.tokenizer.decode(req.token_ids)
            at = min(
                (p for p in (text.find(s) for s in req.stop_texts) if p != -1),
                default=-1,
            )
            if at != -1:
                req.stop_cut = at
                return True
        if len(req.token_ids) >= req.max_new_tokens:
            return True
        prompt_len = req.effective_prompt_len or len(req.prompt_ids)
        # Context-length guard. After a replay, effective_prompt_len
        # already covers the pre-restart tokens (they were re-prefilled),
        # so subtract them from the generated count or the sum would
        # double-count and retire the stream early.
        return (
            prompt_len + len(req.token_ids) - req.replayed_tokens
            >= self.max_len - 1
        )

    def _retire(self, slot: int, seq: _ActiveSeq) -> None:
        req = seq.request
        text = self.tokenizer.decode(req.token_ids) if self.tokenizer else ""
        ids, lps = list(req.token_ids), list(req.token_logprobs)
        tops = list(req.token_top_logprobs) if req.top_logprobs else None
        eos = self.tokenizer.eos_id if self.tokenizer is not None else -1
        if req.stop_cut >= 0:
            # Stop sequence: trim the text at the match and the token/
            # logprob lists to the longest prefix whose decode fits the
            # kept text, so text and logprobs stay aligned.
            text = text[: req.stop_cut]
            keep = 0
            for i in range(1, len(ids) + 1):
                if len(self.tokenizer.decode(ids[:i])) <= req.stop_cut:
                    keep = i
                else:
                    break
            ids, lps = ids[:keep], lps[:keep]
            if tops is not None:
                tops = tops[:keep]
            reason = "stop"
        elif req.stop_on_eos and ids and ids[-1] == eos:
            reason = "stop"
        else:
            reason = "length"  # token budget or context window exhausted
        result = GenerationResult(
            text=text,
            token_ids=ids,
            prompt_tokens=len(req.prompt_ids),
            ttft_s=req.ttft_s,
            duration_s=time.time() - req.enqueued_at,
            truncated=req.truncated,
            token_logprobs=lps,
            finish_reason=reason,
            token_top_logprobs=tops,
            # Deliberate brownout truncation: advertised ONLY when the
            # clamp actually cut the answer short (finish_reason
            # "length") — a stream that hit EOS inside the clamped
            # budget was not truncated by policy.
            brownout=req.brownout_clamped and reason == "length",
        )
        # Summarize BEFORE resolving: a caller that sees the result is
        # guaranteed the flight-recorder entry, histogram records, and
        # spans already exist (the deterministic-test contract; the work
        # is host-side bookkeeping plus a non-blocking exporter enqueue).
        if req.timeline is not None:
            req.timeline.finish("ok", reason, output_tokens=len(ids))
        if self._tenant_ledger is not None:
            self._tenant_ledger.finish_request(req, "ok")
        if not req.future.done():
            req.future.set_result(result)
        req.stream.put(None)  # stream sentinel (after the result resolves)

    def _update_slot_gauges(self) -> None:
        if self._metrics is None:
            return
        in_use = sum(1 for s in self._slots if s is not None)
        self._metrics.set_gauge("app_tpu_kv_slots_in_use", in_use, "model", self.model_name)
        self._metrics.set_gauge(
            "app_tpu_queue_depth", self._pending.qsize(), "batcher", "generate"
        )
        # Saturation signals (device_telemetry): headroom is O(1)
        # arithmetic over the allocator's free count; occupancy and
        # fragmentation are two divisions. All host values already in
        # hand — no device pulls, window granularity.
        self._metrics.set_gauge(
            "app_tpu_hbm_headroom_ratio", self.hbm_headroom_ratio(),
            "model", self.model_name,
        )
        if self.kv_block:
            total, used, cached = self._kv_pool_counts()
            self._metrics.set_gauge(
                "app_tpu_kv_pool_occupancy_ratio", used / max(1, total),
                "model", self.model_name,
            )
            # The used pool's radix-cached (reclaimable-under-pressure)
            # share: high occupancy + high fragmentation = pressure the
            # eviction watermark can relieve; high occupancy + LOW
            # fragmentation = live streams genuinely need the blocks.
            self._metrics.set_gauge(
                "app_tpu_kv_pool_fragmentation_ratio",
                (cached / used) if used else 0.0,
                "model", self.model_name,
            )
        try:
            stats = self._jax.local_devices()[0].memory_stats() or {}
            if "bytes_in_use" in stats:
                self._metrics.set_gauge(
                    "app_tpu_hbm_used_bytes", stats["bytes_in_use"], "chip", "0"
                )
        except Exception:  # graftlint: disable=GL006 — gauge-only path; memory_stats support varies by backend and must never touch token flow
            pass

