"""The fault-tolerant control plane: every signal, one seam (ISSUE 17).

PRs 10–14 grew a rich sensor suite — HBM headroom, per-tenant burn
rates, the brownout ladder, queue depth/throughput, the loop profiler's
``host_overhead_ratio`` — but the closed loops were four ad-hoc
threshold wirings and several signals stayed observe-only. This module
is the one deterministic controller that ingests every signal through a
typed :class:`SignalSource` registry and drives every actuator through
one seam, closing three loops the sensors already paid for:

* **Per-tenant brownout** — per-tenant SLO burn rates (``slo.py``'s
  tenant-tracked rings, judged against the GLOBAL objectives) drive a
  per-tenant degradation ladder mirroring ``brownout.py``'s discipline:
  L1 clamps the burning tenant's ``max_new_tokens``, L2 thins its
  admissions with a deterministic AIMD credit (fraction
  ``budget_factor × CLASS_ADMIT_FRACTION``), L3 sheds its new work
  (429 ``reason=tenant_brownout``). The hog degrades; every other
  tenant's streams stay byte-identical and the POD ladder stays at L0.
* **Host-overhead pressure** — sustained high ``host_overhead_ratio``
  at high loop utilization (the scheduler is busy doing bookkeeping,
  not feeding the device) asserts scale-up pressure through the same
  hysteretic sustain-window discipline as every other loop (GL017).
* **Predictive scaling** — a bounded-window least-squares fit over
  queue-depth samples projects the depth ``horizon_s`` ahead; a
  positive trend crossing the threshold asserts scale-up pressure
  BEFORE the sustained-threshold breach the reactive scaler waits for.
  Stated-clock testable; a hold-down timer stops flapping.
* **Async consumer lag** (ISSUE 18) — sustained request-topic backlog
  on the async serving plane (``serving/async_serving.py``) asserts
  the same scale-up pressure: batch work waiting is idle capacity the
  pool could add, through the identical hysteretic discipline.

**Robustness is the headline.** Every signal read is wrapped in a
staleness/NaN/exception guard: a sensor that goes stale, returns
non-finite values, or raises moves its consumers to last-good-value
(within ``TPU_CONTROL_STALE_S``) and then to **observe-only** — the
loop's actuators all return neutral (no clamp, admit everything, no
pressure), so a lying sensor can never cause a crash, a wedged
scheduler pass, or a 5xx. The degraded-sensor set exports as
``app_tpu_control_signal_health{signal}`` (1 = healthy, 0.5 = serving
last-good, 0 = observe-only) and on ``/debug/control`` next to
per-loop state, last decisions, and hold-down timers. The ``faults``
harness's ``control.signal`` point (stale / NaN / raise / flap) lets
chaos tests prove each guard.

Discipline (shared with ``brownout.py``/``loop_profiler.py``):

* one evaluation per scheduler pass, one clock read (GL011);
* hysteresis with sustain-window anchors everywhere (GL017);
* injectable clock — tests state time, never sleep;
* **off is off**: ``TPU_CONTROL_PLANE=0`` builds nothing, every hook
  degrades to one ``is not None``, and with no tenant above L0 /
  no pressure asserted the actuators are byte-identically neutral.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Any, Callable, Mapping, Optional, Union

from gofr_tpu import faults
from gofr_tpu.analysis import lockcheck
from gofr_tpu.serving.brownout import CLASS_ADMIT_FRACTION, MAX_LEVEL

#: Signal health gauge values (``app_tpu_control_signal_health``).
HEALTH_OK = 1.0          #: fresh, finite sample this pass
HEALTH_LAST_GOOD = 0.5   #: degraded but serving last-good (still acting)
HEALTH_OBSERVE_ONLY = 0.0  #: past the stale window — loop is neutral

#: A signal's sampled value: a scalar or a per-tenant map.
SignalValue = Union[float, dict[str, float]]


class SignalSource:
    """One registered sensor: a name, a zero-arg read callable, a type
    (``scalar`` | ``map``), and the guard state the control plane
    maintains around it (last-good value, staleness, health)."""

    __slots__ = (
        "name", "read", "kind", "stale_after_s",
        "last_good", "last_good_at", "status", "errors", "last_error",
    )

    def __init__(
        self,
        name: str,
        read: Callable[[], Any],
        *,
        kind: str = "scalar",
        stale_after_s: float = 10.0,
    ) -> None:
        if kind not in ("scalar", "map"):
            raise ValueError(f"unknown signal kind {kind!r}")
        self.name = name
        self.read = read
        self.kind = kind
        self.stale_after_s = max(0.0, float(stale_after_s))
        self.last_good: Optional[SignalValue] = None
        self.last_good_at: Optional[float] = None
        #: "ok" | "last_good" | "observe_only"
        self.status = "ok"
        self.errors = 0
        self.last_error = ""

    def health(self) -> float:
        if self.status == "ok":
            return HEALTH_OK
        if self.status == "last_good":
            return HEALTH_LAST_GOOD
        return HEALTH_OBSERVE_ONLY


class _Reading:
    """One pass's guarded sample of one signal."""

    __slots__ = ("value", "usable", "fresh")

    def __init__(
        self, value: Optional[SignalValue], usable: bool, fresh: bool
    ) -> None:
        self.value = value
        #: May a loop ACT on this value? (fresh, or last-good within
        #: the stale window). False → the consuming loop observes only.
        self.usable = usable
        self.fresh = fresh


def _validate(kind: str, raw: Any) -> SignalValue:
    """Clamp a sensor's raw return to its declared type; raises on
    anything non-finite (a lying sensor is an error, not a value)."""
    if kind == "scalar":
        value = float(raw)
        if not math.isfinite(value):
            raise ValueError(f"non-finite scalar {value!r}")
        return value
    if not isinstance(raw, Mapping):
        raise TypeError(f"map signal returned {type(raw).__name__}")
    out: dict[str, float] = {}
    for key, v in raw.items():
        f = float(v)
        if not math.isfinite(f):
            raise ValueError(f"non-finite value for {key!r}")
        out[str(key)] = f
    return out


class _TenantLadder:
    """One tenant's degradation ladder state (the per-tenant mirror of
    ``BrownoutController``'s hysteresis + AIMD, small enough to keep a
    bounded table of)."""

    __slots__ = (
        "level", "budget_factor", "credit", "over_since", "clear_since",
        "last_burn",
    )

    def __init__(self) -> None:
        self.level = 0
        self.budget_factor = 1.0
        #: L2 admission credit: each submit adds the tenant's admit
        #: fraction; a request is admitted when a full credit is
        #: banked. Deterministic thinning — no randomness.
        self.credit = 1.0
        self.over_since: Optional[float] = None
        self.clear_since: Optional[float] = None
        self.last_burn = 0.0


class TenantBrownoutLoop:
    """Per-tenant burn → per-tenant ladder. All state mutation happens
    under the owning :class:`ControlPlane`'s lock."""

    def __init__(
        self,
        *,
        enter_burn: float = 2.0,
        exit_burn: float = 1.0,
        sustain_s: float = 10.0,
        exit_sustain_s: float = 30.0,
        max_new_tokens: int = 256,
        aimd_cut: float = 0.5,
        recover_per_s: float = 0.02,
        table_max: int = 64,
    ) -> None:
        self.enter_burn = max(0.0, float(enter_burn))
        self.exit_burn = min(self.enter_burn, max(0.0, float(exit_burn)))
        self.sustain_s = max(0.0, float(sustain_s))
        self.exit_sustain_s = max(0.0, float(exit_sustain_s))
        self.max_new_tokens = max(0, int(max_new_tokens))
        self.aimd_cut = min(1.0, max(0.05, float(aimd_cut)))
        self.recover_per_s = max(1e-4, float(recover_per_s))
        self.table_max = max(1, int(table_max))
        self.table: dict[str, _TenantLadder] = {}
        self.transitions = {"up": 0, "down": 0}

    def evaluate(
        self, burns: Mapping[str, float], now: float, dt: float
    ) -> list[tuple[str, int, int]]:
        """One control decision per tenant; returns the transitions
        ``(tenant, prev_level, new_level)`` this pass made. Tenants in
        the table but absent from ``burns`` read burn 0 (idle tenants
        recover); tenants beyond the table bound are ignored (bounded
        memory beats complete coverage of a label-cardinality attack).
        """
        moves: list[tuple[str, int, int]] = []
        seen = set(self.table)
        for tenant, burn in burns.items():
            ladder = self.table.get(tenant)
            if ladder is None:
                if len(self.table) >= self.table_max:
                    continue
                ladder = self.table[tenant] = _TenantLadder()
            seen.discard(tenant)
            self._step_tenant(tenant, ladder, burn, now, dt, moves)
        for tenant in seen:
            self._step_tenant(
                tenant, self.table[tenant], 0.0, now, dt, moves
            )
        # Drop fully-recovered idle entries so the table stays
        # O(misbehaving tenants), not O(every tenant ever seen).
        for tenant in [
            t for t, lad in self.table.items()
            if lad.level == 0 and lad.budget_factor >= 1.0
            and t not in burns
        ]:
            del self.table[tenant]
        return moves

    def _step_tenant(
        self,
        tenant: str,
        ladder: _TenantLadder,
        burn: float,
        now: float,
        dt: float,
        moves: list[tuple[str, int, int]],
    ) -> None:
        ladder.last_burn = float(burn)
        over = burn >= self.enter_burn
        clear = burn <= self.exit_burn
        if not over and ladder.budget_factor < 1.0:
            ladder.budget_factor = min(
                1.0, ladder.budget_factor + self.recover_per_s * dt
            )
        if over:
            ladder.clear_since = None
            if ladder.over_since is None:
                ladder.over_since = now
            elif (
                now - ladder.over_since >= self.sustain_s
                and ladder.level < MAX_LEVEL
            ):
                moves.append(
                    (tenant, ladder.level, self._move(ladder, +1))
                )
                ladder.over_since = now  # re-arm for the next rung
        elif clear:
            ladder.over_since = None
            if ladder.clear_since is None:
                ladder.clear_since = now
            elif (
                now - ladder.clear_since >= self.exit_sustain_s
                and ladder.level > 0
            ):
                moves.append(
                    (tenant, ladder.level, self._move(ladder, -1))
                )
                ladder.clear_since = now
        else:
            # Hysteresis dead band: hold, reset both anchors (GL017 —
            # band time counts toward neither sustain window).
            ladder.over_since = None
            ladder.clear_since = None

    def _move(self, ladder: _TenantLadder, direction: int) -> int:
        prev = ladder.level
        ladder.level = min(MAX_LEVEL, max(0, ladder.level + direction))
        if ladder.level != prev:
            if direction > 0 and ladder.level >= 2:
                ladder.budget_factor = max(
                    0.01, ladder.budget_factor * self.aimd_cut
                )
                ladder.credit = 1.0  # L2 entry: first request admits
            if ladder.level == 0:
                ladder.budget_factor = 1.0  # byte-identity at L0
            self.transitions["up" if direction > 0 else "down"] += 1
        return ladder.level


class HostPressureLoop:
    """Sustained high host-overhead ratio at high utilization →
    scale-up pressure. The exit threshold sits a fixed margin below the
    enter one (hysteresis band)."""

    EXIT_MARGIN = 0.1

    def __init__(
        self,
        *,
        ratio: float = 0.85,
        util: float = 0.75,
        sustain_s: float = 30.0,
    ) -> None:
        self.ratio = min(1.0, max(0.0, float(ratio)))
        self.util = min(1.0, max(0.0, float(util)))
        self.exit_ratio = max(0.0, self.ratio - self.EXIT_MARGIN)
        self.sustain_s = max(0.0, float(sustain_s))
        self.pressure = False
        self.over_since: Optional[float] = None
        self.clear_since: Optional[float] = None
        self.last_ratio = 0.0
        self.last_util = 0.0

    def evaluate(self, ratio: float, util: float, now: float) -> bool:
        self.last_ratio = float(ratio)
        self.last_util = float(util)
        over = ratio >= self.ratio and util >= self.util
        clear = ratio <= self.exit_ratio or util < self.util
        if over:
            self.clear_since = None
            if self.over_since is None:
                self.over_since = now
            elif now - self.over_since >= self.sustain_s:
                self.pressure = True
        elif clear:
            self.over_since = None
            if self.clear_since is None:
                self.clear_since = now
            elif now - self.clear_since >= self.sustain_s:
                self.pressure = False
        else:
            self.over_since = None
            self.clear_since = None
        return self.pressure


class AsyncLagLoop:
    """Sustained async consumer lag (request-topic backlog the serving
    plane has not leased; ``serving/async_serving.py``) → scale-up
    pressure. Same hysteretic sustain discipline as
    :class:`HostPressureLoop`; the exit threshold sits at a fixed
    fraction of the enter one so a backlog oscillating at the line
    never flaps the scaler."""

    EXIT_FRACTION = 0.5

    def __init__(
        self, *, depth: float = 64.0, sustain_s: float = 30.0
    ) -> None:
        self.configure(depth, sustain_s)
        self.pressure = False
        self.over_since: Optional[float] = None
        self.clear_since: Optional[float] = None
        self.last_lag = 0.0

    def configure(self, depth: float, sustain_s: float) -> None:
        """Re-point the thresholds (the async plane's config seam runs
        after the control plane is built)."""
        self.depth = max(1.0, float(depth))
        self.exit_depth = self.depth * self.EXIT_FRACTION
        self.sustain_s = max(0.0, float(sustain_s))

    def evaluate(self, lag: float, now: float) -> bool:
        self.last_lag = float(lag)
        over = lag >= self.depth
        clear = lag <= self.exit_depth
        if over:
            self.clear_since = None
            if self.over_since is None:
                self.over_since = now
            elif now - self.over_since >= self.sustain_s:
                self.pressure = True
        elif clear:
            self.over_since = None
            if self.clear_since is None:
                self.clear_since = now
            elif now - self.clear_since >= self.sustain_s:
                self.pressure = False
        else:
            self.over_since = None
            self.clear_since = None
        return self.pressure


class PredictiveLoop:
    """Queue-depth trend fit → early scale-up pressure. A bounded
    sample window, a least-squares slope, and a fixed projection
    horizon: fire when the projected depth crosses the threshold while
    the trend is rising. Deterministic from the stated clock."""

    MIN_SAMPLES = 4

    def __init__(
        self,
        *,
        window_s: float = 60.0,
        horizon_s: float = 30.0,
        depth_threshold: float = 64.0,
        hold_s: float = 30.0,
    ) -> None:
        self.window_s = max(1.0, float(window_s))
        self.horizon_s = max(1.0, float(horizon_s))
        self.depth_threshold = max(1.0, float(depth_threshold))
        self.hold_s = max(0.0, float(hold_s))
        self.samples: deque[tuple[float, float]] = deque()
        self.pressure = False
        self.fired_at: Optional[float] = None
        self.last_slope = 0.0
        self.last_projected = 0.0
        self.last_throughput = 0.0

    def evaluate(
        self, depth: float, throughput: float, now: float
    ) -> bool:
        self.last_throughput = float(throughput)
        self.samples.append((now, float(depth)))
        horizon = now - self.window_s
        while self.samples and self.samples[0][0] < horizon:
            self.samples.popleft()
        slope = self._slope()
        self.last_slope = slope
        projected = depth + slope * self.horizon_s
        self.last_projected = projected
        if (
            len(self.samples) >= self.MIN_SAMPLES
            and slope > 0.0
            and projected >= self.depth_threshold
        ):
            self.pressure = True
            self.fired_at = now
        elif self.pressure and (
            self.fired_at is None or now - self.fired_at >= self.hold_s
        ):
            # Hold-down elapsed and the trend no longer projects a
            # breach: release.
            self.pressure = False
            self.fired_at = None
        return self.pressure

    def _slope(self) -> float:
        """Least-squares depth/second over the retained window — pure
        arithmetic over ≤ O(window/pass) points, no allocation beyond
        the deque itself."""
        n = len(self.samples)
        if n < 2:
            return 0.0
        t0 = self.samples[0][0]
        sum_t = sum_d = sum_tt = sum_td = 0.0
        for t, d in self.samples:
            x = t - t0
            sum_t += x
            sum_d += d
            sum_tt += x * x
            sum_td += x * d
        denom = n * sum_tt - sum_t * sum_t
        if denom <= 1e-12:
            return 0.0
        return (n * sum_td - sum_t * sum_d) / denom


class ControlPlane:
    """The one controller (see the module docstring). ``evaluate`` runs
    on the scheduler thread once per pass; the actuator reads
    (``tenant_admit``, ``tenant_clamp_max_new``, ``scale_pressure``)
    run on submit/probe threads — all state is mutated under one lock,
    and signal reads happen OUTSIDE it (sensors take their own locks;
    holding ours across theirs would mint lock-order edges for free).
    """

    def __init__(
        self,
        model_name: str,
        *,
        stale_s: float = 10.0,
        tenant_enter: float = 2.0,
        tenant_exit: float = 1.0,
        tenant_sustain_s: float = 10.0,
        tenant_exit_sustain_s: float = 30.0,
        tenant_max_new: int = 256,
        tenant_aimd_cut: float = 0.5,
        tenant_recover_per_s: float = 0.02,
        tenant_table_max: int = 64,
        host_ratio: float = 0.85,
        host_util: float = 0.75,
        host_sustain_s: float = 30.0,
        predict_window_s: float = 60.0,
        predict_horizon_s: float = 30.0,
        predict_depth: float = 64.0,
        predict_hold_s: float = 30.0,
        async_lag_depth: float = 64.0,
        async_lag_sustain_s: float = 30.0,
        decision_records: int = 64,
        metrics: Any = None,
        logger: Any = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.model_name = model_name
        self.stale_s = max(0.0, float(stale_s))
        self._metrics = metrics
        self._logger = logger
        self._clock = clock
        self._lock = lockcheck.make_lock("ControlPlane._lock")
        self._signals: dict[str, SignalSource] = {}
        self.tenant_loop = TenantBrownoutLoop(
            enter_burn=tenant_enter,
            exit_burn=tenant_exit,
            sustain_s=tenant_sustain_s,
            exit_sustain_s=tenant_exit_sustain_s,
            max_new_tokens=tenant_max_new,
            aimd_cut=tenant_aimd_cut,
            recover_per_s=tenant_recover_per_s,
            table_max=tenant_table_max,
        )
        self.host_loop = HostPressureLoop(
            ratio=host_ratio, util=host_util, sustain_s=host_sustain_s
        )
        self.predict_loop = PredictiveLoop(
            window_s=predict_window_s,
            horizon_s=predict_horizon_s,
            depth_threshold=predict_depth,
            hold_s=predict_hold_s,
        )
        self.async_loop = AsyncLagLoop(
            depth=async_lag_depth, sustain_s=async_lag_sustain_s
        )
        #: Per-loop mode: "active" | "observe_only" | "off" (no signal
        #: registered for it). Observe-only means every actuator the
        #: loop owns returns neutral — the zero-5xx guarantee.
        self._modes = {
            "tenant_brownout": "off",
            "host_pressure": "off",
            "predictive": "off",
            "async_lag": "off",
        }
        self._decisions: deque[dict[str, Any]] = deque(
            maxlen=max(8, int(decision_records))
        )
        self._passes = 0
        self._eval_errors = 0
        self._last_eval: Optional[float] = None
        self._published_tenants: set[str] = set()

    # -- registry -------------------------------------------------------

    def register(
        self,
        name: str,
        read: Callable[[], Any],
        *,
        kind: str = "scalar",
        stale_after_s: Optional[float] = None,
    ) -> SignalSource:
        """Add one sensor to the typed registry. Registration order is
        boot-deterministic; names are the bounded metric-label set."""
        src = SignalSource(
            name, read, kind=kind,
            stale_after_s=(
                self.stale_s if stale_after_s is None else stale_after_s
            ),
        )
        self._signals[name] = src
        return src

    # -- the guarded read ----------------------------------------------

    def _sample_raw(
        self, src: SignalSource
    ) -> tuple[str, Any]:
        """Read one sensor OUTSIDE the control lock. Returns
        ``("ok", value)`` | ``("stale", None)`` | ``("error", msg)``.
        The ``control.signal`` fault point lets chaos tests substitute
        any failure mode: an armed action returning ``"stale"`` skips
        the read, a returned float (NaN included) replaces the value,
        and an armed ``raises`` exercises the exception guard."""
        try:
            directive = faults.fire("control.signal", signal=src.name)
            if directive == "stale":
                return ("stale", None)
            raw = src.read() if directive is None else directive
            return ("ok", _validate(src.kind, raw))
        except Exception as exc:  # noqa: BLE001 — the guard IS the contract: no sensor failure may escape
            return ("error", f"{type(exc).__name__}: {exc}")

    def _absorb(
        self, src: SignalSource, outcome: tuple[str, Any], now: float
    ) -> _Reading:
        """Fold one raw sample into the source's guard state (call
        under the lock) and return the reading its consumers see."""
        status, payload = outcome
        if status == "ok":
            src.last_good = payload
            src.last_good_at = now
            src.status = "ok"
            src.last_error = ""
            return _Reading(payload, usable=True, fresh=True)
        src.errors += 1
        if status == "error":
            src.last_error = str(payload)
        elif not src.last_error:
            src.last_error = "stale"
        within = (
            src.last_good_at is not None
            and now - src.last_good_at <= src.stale_after_s
        )
        if within:
            src.status = "last_good"
            return _Reading(src.last_good, usable=True, fresh=False)
        src.status = "observe_only"
        return _Reading(src.last_good, usable=False, fresh=False)

    # -- the control pass ----------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> None:
        """One control pass: sample every signal through its guard,
        run the loops whose inputs are usable, publish health/state.
        NEVER raises — a control-plane bug degrades to a logged count,
        not a dead scheduler."""
        t = self._clock() if now is None else now
        try:
            self._evaluate(t)
        except Exception as exc:  # noqa: BLE001 — the scheduler pass must survive any controller bug
            self._eval_errors += 1
            if self._logger is not None:
                self._logger.errorf("control plane pass failed: %s", exc)

    def _evaluate(self, t: float) -> None:
        raw = {
            name: self._sample_raw(src)
            for name, src in self._signals.items()
        }
        moves: list[tuple[str, int, int]] = []
        with self._lock:
            dt = (
                max(0.0, t - self._last_eval)
                if self._last_eval is not None else 0.0
            )
            self._last_eval = t
            self._passes += 1
            readings = {
                name: self._absorb(self._signals[name], raw[name], t)
                for name in raw
            }
            moves = self._run_tenant_loop(readings, t, dt)
            self._run_scale_loops(readings, t)
            decisions = [
                {
                    "t": round(t, 3),
                    "loop": "tenant_brownout",
                    "action": (
                        f"level {prev} -> {new}"
                    ),
                    "tenant": tenant,
                }
                for tenant, prev, new in moves
            ]
            for d in decisions:
                self._decisions.append(d)
        self._publish(moves, t)

    def _run_tenant_loop(
        self, readings: dict[str, _Reading], t: float, dt: float
    ) -> list[tuple[str, int, int]]:
        reading = readings.get("tenant_burn")
        if reading is None:
            self._modes["tenant_brownout"] = "off"
            return []
        if not reading.usable or not isinstance(reading.value, Mapping):
            # Observe-only: hold the table (no climbs, no descents —
            # acting on a dead sensor in EITHER direction is guessing)
            # and let the actuators read neutral.
            self._modes["tenant_brownout"] = "observe_only"
            return []
        self._modes["tenant_brownout"] = "active"
        return self.tenant_loop.evaluate(reading.value, t, dt)

    def _run_scale_loops(
        self, readings: dict[str, _Reading], t: float
    ) -> None:
        ratio = readings.get("host_overhead_ratio")
        util = readings.get("loop_utilization")
        if ratio is None or util is None:
            self._modes["host_pressure"] = "off"
        elif not (ratio.usable and util.usable):
            self._modes["host_pressure"] = "observe_only"
        else:
            self._modes["host_pressure"] = "active"
            assert isinstance(ratio.value, float)
            assert isinstance(util.value, float)
            self.host_loop.evaluate(ratio.value, util.value, t)
        depth = readings.get("queue_depth")
        tput = readings.get("throughput")
        if depth is None:
            self._modes["predictive"] = "off"
        elif not depth.usable:
            self._modes["predictive"] = "observe_only"
        else:
            self._modes["predictive"] = "active"
            assert isinstance(depth.value, float)
            tput_v = (
                tput.value
                if tput is not None and tput.usable
                and isinstance(tput.value, float) else 0.0
            )
            self.predict_loop.evaluate(depth.value, tput_v, t)
        lag = readings.get("async_lag")
        if lag is None:
            self._modes["async_lag"] = "off"
        elif not lag.usable:
            self._modes["async_lag"] = "observe_only"
        else:
            self._modes["async_lag"] = "active"
            assert isinstance(lag.value, float)
            self.async_loop.evaluate(lag.value, t)

    # -- actuator surface (submit / probe threads) ----------------------

    def tenant_level(self, tenant: str) -> int:
        """The tenant's current ladder rung (0 = nominal/unknown)."""
        key = str(tenant or "").lower()
        with self._lock:
            ladder = self.tenant_loop.table.get(key)
            return ladder.level if ladder is not None else 0

    def tenant_clamp_max_new(self, tenant: str, requested: int) -> int:
        """L1+ clamp on the BURNING tenant's generation budget — the
        per-tenant mirror of ``BrownoutController.clamp_max_new``.
        Neutral (identity) below L1, in observe-only mode, and for
        every tenant not on the ladder."""
        key = str(tenant or "").lower()
        with self._lock:
            if self._modes["tenant_brownout"] != "active":
                return int(requested)
            ladder = self.tenant_loop.table.get(key)
            if (
                ladder is None or ladder.level < 1
                or self.tenant_loop.max_new_tokens <= 0
            ):
                return int(requested)
            return min(int(requested), self.tenant_loop.max_new_tokens)

    def tenant_admit(self, tenant: str, slo_class: str) -> bool:
        """May this tenant's request enter the queue? True below L2
        (byte-identical admission) and in observe-only mode; at L2 a
        deterministic credit admits ``budget_factor × class fraction``
        of the tenant's submissions (batch thinned hardest); at L3 the
        tenant's new work is shed outright (fair-share shed — its own
        429s, everyone else's admissions untouched)."""
        key = str(tenant or "").lower()
        with self._lock:
            if self._modes["tenant_brownout"] != "active":
                return True
            ladder = self.tenant_loop.table.get(key)
            if ladder is None or ladder.level < 2:
                return True
            if ladder.level >= MAX_LEVEL:
                return False
            frac = ladder.budget_factor * CLASS_ADMIT_FRACTION.get(
                slo_class, CLASS_ADMIT_FRACTION["standard"]
            )
            ladder.credit += min(1.0, max(0.0, frac))
            if ladder.credit >= 1.0:
                ladder.credit -= 1.0
                return True
            return False

    def tenant_recovery_s(self, tenant: str) -> float:
        """Retry-After floor for a ``tenant_brownout`` shed: one
        exit-sustain period per rung above L1 plus the AIMD credit's
        additive recovery — the per-tenant twin of
        ``BrownoutController.projected_recovery_s``."""
        key = str(tenant or "").lower()
        loop = self.tenant_loop
        with self._lock:
            ladder = loop.table.get(key)
            if ladder is None or ladder.level == 0:
                return 0.0
            rungs = max(0, ladder.level - 1)
            wait = rungs * loop.exit_sustain_s
            wait += (1.0 - ladder.budget_factor) / loop.recover_per_s
            return max(1.0, wait)

    def force_tenant_level(self, tenant: str, level: int) -> None:
        """Jump one tenant's ladder (ops drills / deterministic tests;
        the next pass resumes normal hysteresis from here)."""
        key = str(tenant or "").lower()
        level = min(MAX_LEVEL, max(0, int(level)))
        with self._lock:
            ladder = self.tenant_loop.table.get(key)
            if ladder is None:
                ladder = self.tenant_loop.table[key] = _TenantLadder()
            while ladder.level < level:
                self.tenant_loop._move(ladder, +1)
            while ladder.level > level:
                self.tenant_loop._move(ladder, -1)
            ladder.over_since = None
            ladder.clear_since = None
            if self._modes["tenant_brownout"] == "off":
                self._modes["tenant_brownout"] = "active"

    def scale_pressure(self) -> int:
        """1 while any scaling loop (host-overhead, predictive, or
        async consumer lag) asserts pressure, else 0. Observe-only
        loops assert nothing — neutral is the degraded mode's
        contract."""
        with self._lock:
            host = (
                self._modes["host_pressure"] == "active"
                and self.host_loop.pressure
            )
            predictive = (
                self._modes["predictive"] == "active"
                and self.predict_loop.pressure
            )
            async_lag = (
                self._modes["async_lag"] == "active"
                and self.async_loop.pressure
            )
            return 1 if (host or predictive or async_lag) else 0

    def signal_health(self) -> dict[str, float]:
        """``{signal: health}`` — the exported degraded-sensor set."""
        with self._lock:
            return {
                name: src.health()
                for name, src in self._signals.items()
            }

    # -- publication ----------------------------------------------------

    def _tenant_label(self, tenant: str) -> str:
        """Bounded label mapper (GL016 discipline): only tenants with a
        live ladder entry reach the gauge, and the ladder table is hard
        bounded (``tenant_table_max``, idle entries evicted) — request
        traffic cannot mint unbounded series through this path."""
        return tenant

    def _publish(
        self, moves: list[tuple[str, int, int]], now: float
    ) -> None:
        m = self._metrics
        if m is None:
            return
        with self._lock:
            health = {
                name: src.health()
                for name, src in self._signals.items()
            }
            levels = {
                tenant: ladder.level
                for tenant, ladder in self.tenant_loop.table.items()
            }
            host = (
                self._modes["host_pressure"] == "active"
                and self.host_loop.pressure
            )
            predictive = (
                self._modes["predictive"] == "active"
                and self.predict_loop.pressure
            )
            async_lag = (
                self._modes["async_lag"] == "active"
                and self.async_loop.pressure
            )
        for name, value in health.items():
            m.set_gauge(
                "app_tpu_control_signal_health", value,
                "model", self.model_name, "signal", name,
            )
        # Per-tenant level gauges: the label set is bounded by the
        # ladder table (table_max), and a tenant leaving the table
        # zeroes its gauge first so stale levels never linger.
        for tenant in self._published_tenants - set(levels):
            m.set_gauge(
                "app_tpu_control_tenant_level", 0.0,
                "model", self.model_name,
                "tenant", self._tenant_label(tenant),
            )
        for tenant, level in levels.items():
            m.set_gauge(
                "app_tpu_control_tenant_level", float(level),
                "model", self.model_name,
                "tenant", self._tenant_label(tenant),
            )
        self._published_tenants = set(levels)
        m.set_gauge(
            "app_tpu_control_scale_pressure", 1.0 if host else 0.0,
            "model", self.model_name, "source", "host",
        )
        m.set_gauge(
            "app_tpu_control_scale_pressure",
            1.0 if predictive else 0.0,
            "model", self.model_name, "source", "predictive",
        )
        m.set_gauge(
            "app_tpu_control_scale_pressure",
            1.0 if async_lag else 0.0,
            "model", self.model_name, "source", "async",
        )
        for _tenant, prev, new in moves:
            m.increment_counter(
                "app_tpu_control_actions_total",
                "model", self.model_name,
                "loop", "tenant_brownout",
                "action", "up" if new > prev else "down",
            )

    def note_action(self, loop: str, action: str) -> None:
        """Count one actuation (clamp/shed) from the engine's hooks —
        the bounded (loop, action) label pair."""
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_control_actions_total",
                "model", self.model_name, "loop", loop, "action", action,
            )
        with self._lock:
            self._decisions.append({
                "t": round(self._clock(), 3),
                "loop": loop,
                "action": action,
            })

    # -- rendering ------------------------------------------------------

    def describe(self) -> dict[str, Any]:
        """The compact health-detail form (rides probes — the headroom
        idiom): scale pressure, degraded sensors, browning tenants."""
        with self._lock:
            degraded = sorted(
                name for name, src in self._signals.items()
                if src.status != "ok"
            )
            browned = sum(
                1 for lad in self.tenant_loop.table.values()
                if lad.level > 0
            )
            host = (
                self._modes["host_pressure"] == "active"
                and self.host_loop.pressure
            )
            predictive = (
                self._modes["predictive"] == "active"
                and self.predict_loop.pressure
            )
            async_lag = (
                self._modes["async_lag"] == "active"
                and self.async_loop.pressure
            )
            return {
                "scale_pressure": (
                    1 if (host or predictive or async_lag) else 0
                ),
                "degraded_signals": degraded,
                "tenants_browned_out": browned,
            }

    def snapshot(self) -> dict[str, Any]:
        """The full ``/debug/control`` form: per-signal guard state,
        per-loop mode + state + hold-down timers, the decision ring."""
        t = self._clock()
        with self._lock:
            signals = {
                name: {
                    "kind": src.kind,
                    "status": src.status,
                    "health": src.health(),
                    "stale_after_s": src.stale_after_s,
                    "errors": src.errors,
                    "last_error": src.last_error,
                    "age_s": (
                        None if src.last_good_at is None
                        else round(max(0.0, t - src.last_good_at), 3)
                    ),
                }
                for name, src in self._signals.items()
            }
            tenant = {
                "mode": self._modes["tenant_brownout"],
                "enter_burn": self.tenant_loop.enter_burn,
                "exit_burn": self.tenant_loop.exit_burn,
                "sustain_s": self.tenant_loop.sustain_s,
                "exit_sustain_s": self.tenant_loop.exit_sustain_s,
                "max_new_tokens": self.tenant_loop.max_new_tokens,
                "aimd_cut": self.tenant_loop.aimd_cut,
                "table_max": self.tenant_loop.table_max,
                "transitions": dict(self.tenant_loop.transitions),
                "tenants": {
                    name: {
                        "level": lad.level,
                        "budget_factor": round(lad.budget_factor, 6),
                        "last_burn": round(lad.last_burn, 6),
                    }
                    for name, lad in self.tenant_loop.table.items()
                },
            }
            host = {
                "mode": self._modes["host_pressure"],
                "pressure": self.host_loop.pressure,
                "ratio_enter": self.host_loop.ratio,
                "ratio_exit": self.host_loop.exit_ratio,
                "util_floor": self.host_loop.util,
                "sustain_s": self.host_loop.sustain_s,
                "last_ratio": round(self.host_loop.last_ratio, 6),
                "last_util": round(self.host_loop.last_util, 6),
                "over_for_s": (
                    None if self.host_loop.over_since is None
                    else round(max(0.0, t - self.host_loop.over_since), 3)
                ),
            }
            predictive = {
                "mode": self._modes["predictive"],
                "pressure": self.predict_loop.pressure,
                "window_s": self.predict_loop.window_s,
                "horizon_s": self.predict_loop.horizon_s,
                "depth_threshold": self.predict_loop.depth_threshold,
                "hold_s": self.predict_loop.hold_s,
                "samples": len(self.predict_loop.samples),
                "last_slope": round(self.predict_loop.last_slope, 6),
                "last_projected": round(
                    self.predict_loop.last_projected, 3
                ),
                "hold_down_left_s": (
                    None if self.predict_loop.fired_at is None
                    else round(max(
                        0.0,
                        self.predict_loop.hold_s
                        - (t - self.predict_loop.fired_at),
                    ), 3)
                ),
            }
            async_lag = {
                "mode": self._modes["async_lag"],
                "pressure": self.async_loop.pressure,
                "depth_enter": self.async_loop.depth,
                "depth_exit": self.async_loop.exit_depth,
                "sustain_s": self.async_loop.sustain_s,
                "last_lag": round(self.async_loop.last_lag, 3),
                "over_for_s": (
                    None if self.async_loop.over_since is None
                    else round(
                        max(0.0, t - self.async_loop.over_since), 3
                    )
                ),
            }
            return {
                "enabled": True,
                "passes": self._passes,
                "eval_errors": self._eval_errors,
                "stale_s": self.stale_s,
                "signals": signals,
                "loops": {
                    "tenant_brownout": tenant,
                    "host_pressure": host,
                    "predictive": predictive,
                    "async_lag": async_lag,
                },
                "decisions": list(self._decisions),
            }
