"""Handler adapter + built-in routes (reference ``pkg/gofr/handler.go``).

Wraps a user handler — sync or async ``fn(ctx) -> result`` (errors are
raised, not returned) — into the server's async handler: build the Context,
open the per-handler span (reference ``handler.go:36``), invoke, and let the
Responder shape the wire response. Sync handlers run on a thread pool so
blocking datasource calls don't stall the event loop (the role goroutines
play in the reference).

Built-ins: ``/.well-known/health`` (aggregate container health),
``/.well-known/alive``, favicon (reference ``handler.go:40-64``).
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
from typing import Callable

from gofr_tpu.context import Context
from gofr_tpu.http.proto import RawRequest, Response
from gofr_tpu.http.request import Request
from gofr_tpu.http.responder import Responder
from gofr_tpu.tracing import get_tracer


def wrap_handler(fn: Callable, container) -> Callable:
    """User handler → async ``(RawRequest) -> Response``."""

    is_async = inspect.iscoroutinefunction(fn)

    async def handler(raw: RawRequest) -> Response:
        request = Request(raw)
        responder = Responder(method=raw.method)
        span = raw.ctx_data.get("span")
        ctx = Context(request, container, responder, span=span)

        handler_span = get_tracer().start_span("gofr-handler", parent=span)
        try:
            if is_async:
                result = await fn(ctx)
            else:
                loop = asyncio.get_running_loop()
                # Copy context so ctx.trace() in threads parents correctly.
                cv_ctx = contextvars.copy_context()
                result = await loop.run_in_executor(None, cv_ctx.run, fn, ctx)
            error = None
        except Exception as exc:
            result, error = None, exc
            if not hasattr(exc, "status_code"):
                raise  # unexpected → panic-recovery middleware logs + 500
        finally:
            handler_span.end()
        return responder.respond(result, error)

    return handler


# -- built-in routes (reference handler.go:40-64) --------------------------


def health_handler(container):
    async def handler(ctx) -> dict:  # noqa: ARG001
        import asyncio

        # Health aggregation makes blocking HTTP probes to service
        # dependencies; run it off the event loop or a dependency pointing
        # back at this app (reference examples do exactly that) deadlocks.
        return await asyncio.get_running_loop().run_in_executor(
            None, container.health
        )

    return handler


async def alive_handler(ctx) -> dict:  # noqa: ARG001
    return {"status": "UP"}


def favicon_handler(ctx):  # noqa: ARG001
    from gofr_tpu.static import FAVICON

    from gofr_tpu.http.response import File

    return File(content=FAVICON, content_type="image/x-icon")
