"""gRPC server tier (reference: ``pkg/gofr/grpc.go`` + ``grpc/log.go``).

An asyncio gRPC server with recovery + logging interceptors (the reference's
interceptor chain, ``grpc.go:23-26``), started only when services are
registered (``gofr.go:150-157``). Ships a built-in inference service
(unary + server-streaming generate, embed, classify) using JSON-over-bytes
messages — no codegen toolchain required in this environment.
"""

from gofr_tpu.grpc.server import GRPCServer, json_method_handlers
from gofr_tpu.grpc.inference import add_inference_service, InferenceClient

__all__ = [
    "GRPCServer",
    "json_method_handlers",
    "add_inference_service",
    "InferenceClient",
]
