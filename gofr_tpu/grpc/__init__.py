"""gRPC server tier (reference: ``pkg/gofr/grpc.go`` + ``grpc/log.go``).

An asyncio gRPC server with recovery + logging interceptors (the reference's
interceptor chain, ``grpc.go:23-26``), started only when services are
registered (``gofr.go:150-157``). Ships the built-in inference service in
two flavors sharing :9000:

* **typed protobuf** ``gofr.tpu.v1.Inference`` — the production contract
  (``proto/inference.proto`` → protoc-generated ``inference_pb2`` +
  stubs), interoperable with any stock gRPC client (the reference's
  generated-stub pattern, ``grpc.go:15-46``);
* **JSON-over-bytes** ``gofr.tpu.Inference`` — codegen-free exploration
  surface.
"""

from gofr_tpu.grpc.server import GRPCServer, json_method_handlers
from gofr_tpu.grpc.inference import add_inference_service, InferenceClient
from gofr_tpu.grpc.inference_typed import (
    TypedInferenceServicer,
    add_typed_inference_service,
)

__all__ = [
    "GRPCServer",
    "json_method_handlers",
    "add_inference_service",
    "InferenceClient",
    "TypedInferenceServicer",
    "add_typed_inference_service",
]
