"""gRPC stubs for ``gofr.tpu.v1.Inference`` (``proto/inference.proto``).

Hand-written in the exact layout ``grpc_tools.protoc`` emits (Stub /
Servicer / add_*_to_server / static service descriptor) because this image
ships ``protoc`` without the grpcio-tools plugin; the message classes in
``inference_pb2.py`` ARE protoc-generated. A stock ``grpc`` client uses
this file exactly like generated code:

    channel = grpc.insecure_channel(addr)
    stub = inference_pb2_grpc.InferenceStub(channel)
    reply = stub.Generate(inference_pb2.GenerateRequest(prompt="hi"))

Reference parity: the generated-stub service pattern of
``/root/reference/pkg/gofr/grpc.go:15-46`` and
``examples/grpc-server/customer/grpc.pb.go``.
"""

from __future__ import annotations

import grpc

from gofr_tpu.grpc import inference_pb2

_SERVICE = "gofr.tpu.v1.Inference"


class InferenceStub:
    """Client stub; same surface as grpc_tools-generated code."""

    def __init__(self, channel: grpc.Channel) -> None:
        self.Generate = channel.unary_unary(
            f"/{_SERVICE}/Generate",
            request_serializer=inference_pb2.GenerateRequest.SerializeToString,
            response_deserializer=inference_pb2.GenerateReply.FromString,
        )
        self.GenerateStream = channel.unary_stream(
            f"/{_SERVICE}/GenerateStream",
            request_serializer=inference_pb2.GenerateRequest.SerializeToString,
            response_deserializer=inference_pb2.TokenChunk.FromString,
        )
        self.Embed = channel.unary_unary(
            f"/{_SERVICE}/Embed",
            request_serializer=inference_pb2.EmbedRequest.SerializeToString,
            response_deserializer=inference_pb2.EmbedReply.FromString,
        )
        self.Classify = channel.unary_unary(
            f"/{_SERVICE}/Classify",
            request_serializer=inference_pb2.ClassifyRequest.SerializeToString,
            response_deserializer=inference_pb2.ClassifyReply.FromString,
        )
        self.Health = channel.unary_unary(
            f"/{_SERVICE}/Health",
            request_serializer=inference_pb2.HealthRequest.SerializeToString,
            response_deserializer=inference_pb2.HealthReply.FromString,
        )


class InferenceServicer:
    """Service base class; override the methods you implement."""

    async def Generate(self, request, context):
        await context.abort(grpc.StatusCode.UNIMPLEMENTED, "Generate")

    async def GenerateStream(self, request, context):
        await context.abort(grpc.StatusCode.UNIMPLEMENTED, "GenerateStream")
        yield  # pragma: no cover — makes this an async generator

    async def Embed(self, request, context):
        await context.abort(grpc.StatusCode.UNIMPLEMENTED, "Embed")

    async def Classify(self, request, context):
        await context.abort(grpc.StatusCode.UNIMPLEMENTED, "Classify")

    async def Health(self, request, context):
        await context.abort(grpc.StatusCode.UNIMPLEMENTED, "Health")


def add_InferenceServicer_to_server(servicer, server) -> None:
    rpc_method_handlers = {
        "Generate": grpc.unary_unary_rpc_method_handler(
            servicer.Generate,
            request_deserializer=inference_pb2.GenerateRequest.FromString,
            response_serializer=inference_pb2.GenerateReply.SerializeToString,
        ),
        "GenerateStream": grpc.unary_stream_rpc_method_handler(
            servicer.GenerateStream,
            request_deserializer=inference_pb2.GenerateRequest.FromString,
            response_serializer=inference_pb2.TokenChunk.SerializeToString,
        ),
        "Embed": grpc.unary_unary_rpc_method_handler(
            servicer.Embed,
            request_deserializer=inference_pb2.EmbedRequest.FromString,
            response_serializer=inference_pb2.EmbedReply.SerializeToString,
        ),
        "Classify": grpc.unary_unary_rpc_method_handler(
            servicer.Classify,
            request_deserializer=inference_pb2.ClassifyRequest.FromString,
            response_serializer=inference_pb2.ClassifyReply.SerializeToString,
        ),
        "Health": grpc.unary_unary_rpc_method_handler(
            servicer.Health,
            request_deserializer=inference_pb2.HealthRequest.FromString,
            response_serializer=inference_pb2.HealthReply.SerializeToString,
        ),
    }
    generic_handler = grpc.method_handlers_generic_handler(
        _SERVICE, rpc_method_handlers
    )
    server.add_generic_rpc_handlers((generic_handler,))
