"""Typed protobuf implementation of ``gofr.tpu.v1.Inference``.

The production gRPC surface (VERDICT r1 missing #1): any stock gRPC client
with the generated stubs interoperates. The JSON service
(``grpc/inference.py``, ``gofr.tpu.Inference``) stays registered alongside
for curl-style exploration — the two live under different proto packages
so both can share :9000.
"""

from __future__ import annotations

import json

import numpy as np

from gofr_tpu.errors import GofrError
from gofr_tpu.grpc import inference_pb2 as pb
from gofr_tpu.grpc.inference_pb2_grpc import (
    InferenceServicer as _Base,
)
from gofr_tpu.grpc.inference_pb2_grpc import (
    add_InferenceServicer_to_server,
)

__all__ = ["TypedInferenceServicer", "add_typed_inference_service"]


class TypedInferenceServicer(_Base):
    def __init__(self, engine, tokenizer=None) -> None:
        self.engine = engine
        self.tokenizer = tokenizer or engine.tokenizer

    def _gen_kwargs(self, request, context=None) -> tuple:
        from gofr_tpu.grpc.server import (
            deadline_from_context,
            slo_class_from_context,
            tenant_from_context,
        )

        prompt = (
            list(request.prompt_ids) if request.prompt_ids else request.prompt
        )
        kw = {
            "max_new_tokens": request.max_new_tokens or 128,
            "temperature": request.temperature,
            "stop_on_eos": request.stop_on_eos,
            "stop": list(request.stop),
        }
        if request.top_p:  # proto default 0 = "not set"
            kw["top_p"] = request.top_p
        if request.adapter:
            kw["adapter"] = request.adapter
        if context is not None:
            # Per-tenant admission quotas (TPU_TENANT_QUEUE_MAX): the
            # x-tenant-id metadata is the gRPC twin of the HTTP header.
            tenant = tenant_from_context(context)
            if tenant:
                kw["tenant"] = tenant
            # Brownout SLO class (x-slo-class): priority-aware shedding
            # under overload (serving/brownout.py).
            slo_class = slo_class_from_context(context)
            if slo_class:
                kw["slo_class"] = slo_class
            # Caller's gRPC deadline → engine Deadline: when it expires
            # the scheduler retires the sequence and frees its KV blocks
            # instead of decoding past an RPC nobody is waiting on.
            remaining = deadline_from_context(context)
            if remaining is not None:
                kw["deadline_s"] = remaining
        return prompt, kw

    async def Generate(self, request, context):
        from gofr_tpu.grpc.server import grpc_status_code

        prompt, kw = self._gen_kwargs(request, context)
        if self.engine.family == "seq2seq":
            text, ids = await self.engine.seq2seq_text(prompt)
            return pb.GenerateReply(
                text=text, tokens=len(ids), finish_reason="stop"
            )
        try:
            result = await self.engine.generate(prompt, **kw)
        except GofrError as exc:
            await context.abort(grpc_status_code(exc), str(exc))
        return pb.GenerateReply(
            text=result.text,
            tokens=len(result.token_ids),
            ttft_ms=round(result.ttft_s * 1e3, 3),
            tokens_per_sec=round(result.tokens_per_sec, 3),
            truncated=result.truncated,
            finish_reason=result.finish_reason,
            token_logprobs=[round(lp, 6) for lp in result.token_logprobs],
        )

    async def GenerateStream(self, request, context):
        import grpc

        from gofr_tpu.grpc.server import grpc_status_code
        from gofr_tpu.serving.stream_text import (
            stream_generation,
            stream_seq2seq,
        )

        if self.engine.family == "seq2seq":
            # Stepped decode: chunks of tokens stream as the engine
            # produces them (r4 VERDICT weak #7), via the shared shaping
            # helper so the surfaces cannot drift.
            prompt, _ = self._gen_kwargs(request)
            async for ev in stream_seq2seq(self.engine, prompt, self.tokenizer):
                if ev["type"] == "piece":
                    yield pb.TokenChunk(token=ev["token"], text=ev["text"])
                else:
                    yield pb.TokenChunk(
                        done=True, tokens=ev["tokens"],
                        ttft_ms=ev["ttft_ms"],
                        finish_reason=ev["finish_reason"],
                    )
            return

        prompt, kw = self._gen_kwargs(request, context)
        try:
            async for ev in stream_generation(
                self.engine, prompt, kw, self.tokenizer
            ):
                if ev["type"] == "piece":
                    yield pb.TokenChunk(token=ev["token"], text=ev["text"])
                else:
                    yield pb.TokenChunk(
                        done=True,
                        tokens=ev["tokens"],
                        ttft_ms=ev["ttft_ms"],
                        finish_reason=ev["finish_reason"],
                    )
        except GofrError as exc:
            await context.abort(grpc_status_code(exc), str(exc))
        except Exception as exc:  # noqa: BLE001 — engine died mid-stream
            await context.abort(grpc.StatusCode.INTERNAL, str(exc))

    async def Embed(self, request, context):
        emb = await self.engine.embed(request.text)
        return pb.EmbedReply(embedding=np.asarray(emb, dtype=np.float32))

    async def Classify(self, request, context):
        image = np.asarray(request.image, dtype=np.float32)
        if request.shape:
            image = image.reshape(tuple(request.shape))
        logits = np.asarray(await self.engine.classify(image))
        return pb.ClassifyReply(
            label=int(np.argmax(logits)), logits=logits.astype(np.float32)
        )

    async def Health(self, request, context):
        h = self.engine.health_check()
        return pb.HealthReply(
            status=h.get("status", "DOWN"),
            details_json=json.dumps(h.get("details", {})),
        )


def add_typed_inference_service(servicer, server) -> None:
    """``App.register_service`` adapter. Two-arg (servicer, server) —
    the protoc-codegen convention, which ``GRPCServer.start`` detects by
    arity (``grpc/server.py``)."""
    add_InferenceServicer_to_server(servicer, server)
