"""Asyncio gRPC server with logging/recovery interceptors.

Reference parity: ``grpc.go:15-46`` (server start gated on registered
services) and ``grpc/log.go:58-96`` (per-RPC span + structured RPCLog with
status). Improvement over the reference: handlers here DO get container
access (SURVEY §3.3 flags the asymmetry as worth fixing — the reference
passes impls straight through with no gofr context).
"""

from __future__ import annotations

import time
import traceback
from typing import Optional

import grpc

from gofr_tpu.tracing import extract_traceparent, get_tracer


def grpc_status_code(exc: BaseException) -> "grpc.StatusCode":
    """Framework error → gRPC status, honoring the resilience statuses:
    shed (429) → RESOURCE_EXHAUSTED, deadline (504) → DEADLINE_EXCEEDED,
    cancelled (499) → CANCELLED, draining (503) and replica-pool
    exhaustion (502, ErrorNoHealthyReplica) → UNAVAILABLE; the rest
    keep the historical 4xx→INVALID_ARGUMENT / 5xx→INTERNAL split."""
    status = getattr(exc, "status_code", 500)
    if status == 429:
        return grpc.StatusCode.RESOURCE_EXHAUSTED
    if status == 499:
        return grpc.StatusCode.CANCELLED
    if status in (502, 503):
        return grpc.StatusCode.UNAVAILABLE
    if status == 504:
        return grpc.StatusCode.DEADLINE_EXCEEDED
    if status < 500:
        return grpc.StatusCode.INVALID_ARGUMENT
    return grpc.StatusCode.INTERNAL


def tenant_from_context(context) -> str:
    """The ``x-tenant-id`` invocation-metadata value ("" when absent) —
    the gRPC twin of the HTTP header feeding per-tenant admission
    quotas (``TPU_TENANT_QUEUE_MAX``)."""
    meta = getattr(context, "invocation_metadata", None)
    if not callable(meta):
        return ""
    try:
        for key, value in meta() or ():
            if str(key).lower() == "x-tenant-id":
                return str(value)
    except Exception:  # graftlint: disable=GL006 — absent/stub metadata APIs mean "untenanted", not an error
        return ""
    return ""


def slo_class_from_context(context) -> str:
    """The ``x-slo-class`` invocation-metadata value ("" when absent) —
    the gRPC twin of the ``X-SLO-Class`` HTTP header feeding the
    brownout controller's priority-aware shedding
    (``serving/brownout.py``: batch sheds first, interactive last)."""
    meta = getattr(context, "invocation_metadata", None)
    if not callable(meta):
        return ""
    try:
        for key, value in meta() or ():
            if str(key).lower() == "x-slo-class":
                return str(value)
    except Exception:  # graftlint: disable=GL006 — absent/stub metadata APIs mean "standard class", not an error
        return ""
    return ""


def deadline_from_context(context) -> Optional[float]:
    """Seconds remaining on the caller's gRPC deadline, or None. The
    servicers turn this into a ``Deadline`` on engine submits so an
    expired RPC's sequence retires mid-decode server-side too."""
    tr = getattr(context, "time_remaining", None)
    if not callable(tr):
        return None
    try:
        remaining = tr()
    except Exception:  # graftlint: disable=GL006 — absent/stub deadline APIs mean "no deadline", not an error
        return None
    if remaining is None or remaining <= 0:
        return None
    return float(remaining)


class RPCLog:
    """Structured RPC log (reference ``grpc/log.go:22-28``)."""

    def __init__(self, method: str, status: str, duration_us: int, trace_id: str) -> None:
        self.rpc = method
        self.status = status
        self.duration = duration_us
        self.trace_id = trace_id

    def to_log_dict(self) -> dict:
        return {
            "rpc": self.rpc,
            "status": self.status,
            "duration": self.duration,
            "trace_id": self.trace_id,
        }

    def pretty_print(self, fp) -> None:
        fp.write(
            f"\x1b[38;5;8mRPC\x1b[0m {self.duration:>8}µs {self.status:>2} {self.rpc}\n"
        )


class _LoggingInterceptor(grpc.aio.ServerInterceptor):
    """Span + RPCLog per call, panic recovery → INTERNAL
    (reference ``grpc/log.go:58-96`` + grpc_recovery)."""

    def __init__(self, logger) -> None:
        self._logger = logger

    async def intercept_service(self, continuation, handler_call_details):
        handler = await continuation(handler_call_details)
        if handler is None:
            return None
        method = handler_call_details.method
        logger = self._logger
        # W3C trace adoption from gRPC invocation metadata (the HTTP
        # middleware's twin): a caller-supplied ``traceparent`` makes
        # this RPC's span — and every engine phase span beneath it — a
        # child in the CALLER's trace instead of a fresh root.
        trace_id = parent_id = None
        try:
            md = {
                str(k).lower(): str(v)
                for k, v in (handler_call_details.invocation_metadata or ())
            }
            trace_id, parent_id = extract_traceparent(md)
        except Exception:  # graftlint: disable=GL006 — absent/stub metadata APIs mean "no caller trace context", not an error
            pass

        def wrap_unary(behavior):
            async def wrapped(request, context):
                span = get_tracer().start_span(
                    f"gRPC {method}",
                    trace_id=trace_id, parent_span_id=parent_id,
                )
                start = time.time()
                status = "OK"
                try:
                    return await behavior(request, context)
                except Exception:
                    status = "INTERNAL"
                    logger.errorf(
                        "rpc %s panicked:\n%s", method, traceback.format_exc()
                    )
                    await context.abort(grpc.StatusCode.INTERNAL, "internal error")
                finally:
                    span.end()
                    logger.info(
                        RPCLog(method, status, int((time.time() - start) * 1e6), span.trace_id)
                    )

            return wrapped

        def wrap_stream(behavior):
            async def wrapped(request, context):
                span = get_tracer().start_span(
                    f"gRPC {method}",
                    trace_id=trace_id, parent_span_id=parent_id,
                )
                start = time.time()
                status = "OK"
                try:
                    async for item in behavior(request, context):
                        yield item
                except Exception:
                    status = "INTERNAL"
                    logger.errorf(
                        "rpc %s panicked:\n%s", method, traceback.format_exc()
                    )
                    await context.abort(grpc.StatusCode.INTERNAL, "internal error")
                finally:
                    span.end()
                    logger.info(
                        RPCLog(method, status, int((time.time() - start) * 1e6), span.trace_id)
                    )

            return wrapped

        if handler.unary_unary is not None:
            return grpc.unary_unary_rpc_method_handler(
                wrap_unary(handler.unary_unary),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        if handler.unary_stream is not None:
            return grpc.unary_stream_rpc_method_handler(
                wrap_stream(handler.unary_stream),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        return handler


def json_method_handlers(service_name: str, unary: dict, streams: dict | None = None):
    """Build a generic handler for a service whose messages are JSON bytes."""
    import json

    def ser(obj) -> bytes:
        return json.dumps(obj, default=str).encode()

    def des(data: bytes):
        return json.loads(data or b"{}")

    handlers = {}
    for name, fn in unary.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=des, response_serializer=ser
        )
    for name, fn in (streams or {}).items():
        handlers[name] = grpc.unary_stream_rpc_method_handler(
            fn, request_deserializer=des, response_serializer=ser
        )
    return grpc.method_handlers_generic_handler(service_name, handlers)


class GRPCServer:
    def __init__(self, port: int, logger, container=None) -> None:
        self.port = port
        self._logger = logger
        self.container = container
        self._server: Optional[grpc.aio.Server] = None
        self._registrations: list = []

    def register(self, add_fn, servicer) -> None:
        """add_fn(server, servicer, container) or codegen add_*_to_server."""
        self._registrations.append((add_fn, servicer))

    async def start(self) -> None:
        self._server = grpc.aio.server(
            interceptors=[_LoggingInterceptor(self._logger)]
        )
        import inspect

        for add_fn, servicer in self._registrations:
            # Two calling conventions: this framework's
            # add_fn(server, servicer, container) vs protoc codegen's
            # add_*_to_server(servicer, server). Decide by arity, not by
            # catching TypeError (which would swallow real bugs in add_fn).
            try:
                n_params = len(inspect.signature(add_fn).parameters)
            except (TypeError, ValueError):
                n_params = 3
            if n_params >= 3:
                add_fn(self._server, servicer, self.container)
            else:
                add_fn(servicer, self._server)
        bound = self._server.add_insecure_port(f"[::]:{self.port}")
        self.port = bound
        await self._server.start()
        self._logger.infof("gRPC server started on :%d", self.port)

    async def stop(self, grace: float = 5.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)
