"""Built-in gRPC inference service (BASELINE.json configs 3 and 5:
BERT embeddings over gRPC unary, Llama chat over gRPC stream).

Service ``gofr.tpu.Inference`` with JSON messages:

* ``Generate``  (unary)  {prompt, max_new_tokens?, temperature?,
  stop? (string or list), top_p?} →
  {text, tokens, ttft_ms, tokens_per_sec, finish_reason}
* ``GenerateStream`` (server streaming) same request → stream of
  {token, text} chunks (stop-trimmed, identical text to the unary
  reply) then a final {done: true, ttft_ms, tokens, finish_reason}
* ``Embed``    (unary)  {text} → {embedding}
* ``Classify`` (unary)  {image: [[...]] nested lists or flat+shape} →
  {class, logits}
* ``Health``   (unary)  {} → container health
"""

from __future__ import annotations

import json

import grpc
import numpy as np

from gofr_tpu.errors import GofrError
from gofr_tpu.grpc.server import (
    deadline_from_context,
    grpc_status_code,
    json_method_handlers,
)

SERVICE = "gofr.tpu.Inference"


class InferenceServicer:
    def __init__(self, engine, tokenizer=None) -> None:
        self.engine = engine
        self.tokenizer = tokenizer or engine.tokenizer

    def _gen_kwargs(self, request, stream: bool, context=None) -> dict:
        from gofr_tpu.grpc.server import (
            slo_class_from_context,
            tenant_from_context,
        )
        from gofr_tpu.serving.stream_text import normalize_stop

        kw = dict(
            max_new_tokens=int(request.get("max_new_tokens", 128)),
            temperature=float(request.get("temperature", 0.0)),
            stop_on_eos=bool(request.get("stop_on_eos", not stream)),
            stop=normalize_stop(request.get("stop")),
        )
        if context is not None:
            # Per-tenant admission quotas (TPU_TENANT_QUEUE_MAX): the
            # x-tenant-id metadata is the gRPC twin of the HTTP header.
            tenant = tenant_from_context(context)
            if tenant:
                kw["tenant"] = tenant
            # Brownout SLO class (x-slo-class): priority-aware shedding
            # under overload (serving/brownout.py).
            slo_class = slo_class_from_context(context)
            if slo_class:
                kw["slo_class"] = slo_class
        if request.get("top_p") is not None:
            kw["top_p"] = float(request["top_p"])
        if request.get("adapter"):
            kw["adapter"] = str(request["adapter"])
        # Deadline propagation: an explicit timeout_s field wins, else
        # the caller's gRPC deadline — either way the engine retires the
        # sequence mid-decode when it expires (scheduler lifecycle reap).
        if request.get("timeout_s") is not None:
            kw["deadline_s"] = float(request["timeout_s"])
        elif context is not None:
            remaining = deadline_from_context(context)
            if remaining is not None:
                kw["deadline_s"] = remaining
        return kw

    async def Generate(self, request, context):
        if self.engine.family == "seq2seq":
            # T5-style text-to-text rides the same RPC: prompt in,
            # generated text out (sampling knobs don't apply to the
            # greedy seq2seq path).
            text, ids = await self.engine.seq2seq_text(
                request.get("prompt", "")
            )
            return {
                "text": text,
                "tokens": len(ids),
                "finish_reason": "stop",
            }
        try:
            result = await self.engine.generate(
                request.get("prompt", ""),
                **self._gen_kwargs(request, False, context),
            )
        except GofrError as exc:
            await context.abort(grpc_status_code(exc), str(exc))
        return {
            "text": result.text,
            "tokens": len(result.token_ids),
            "ttft_ms": round(result.ttft_s * 1e3, 2),
            "tokens_per_sec": round(result.tokens_per_sec, 2),
            "finish_reason": result.finish_reason,
        }

    async def GenerateStream(self, request, context):
        from gofr_tpu.serving.stream_text import (
            stream_generation,
            stream_seq2seq,
        )

        if self.engine.family == "seq2seq":
            # Stepped decode: the engine advances the answer buffer a
            # chunk of greedy steps per dispatch and tokens stream as
            # they are produced (r4 VERDICT weak #7 — a streaming API
            # must not buffer the whole answer).
            async for ev in stream_seq2seq(
                self.engine, request.get("prompt", ""), self.tokenizer
            ):
                if ev["type"] == "piece":
                    yield {"token": ev["token"], "text": ev["text"]}
                else:
                    yield {
                        "done": True,
                        "tokens": ev["tokens"],
                        "ttft_ms": ev["ttft_ms"],
                        "finish_reason": ev["finish_reason"],
                    }
            return
        try:
            async for ev in stream_generation(
                self.engine, request.get("prompt", ""),
                self._gen_kwargs(request, True, context), self.tokenizer,
            ):
                if ev["type"] == "piece":
                    yield {"token": ev["token"], "text": ev["text"]}
                else:
                    yield {
                        "done": True,
                        "tokens": ev["tokens"],
                        "ttft_ms": ev["ttft_ms"],
                        "finish_reason": ev["finish_reason"],
                    }
        except GofrError as exc:
            await context.abort(grpc_status_code(exc), str(exc))

    async def Embed(self, request, context):
        emb = await self.engine.embed(request.get("text", ""))
        return {"embedding": np.asarray(emb).tolist()}

    async def Classify(self, request, context):
        image = np.asarray(request.get("image"), dtype=np.float32)
        if "shape" in request:
            image = image.reshape(request["shape"])
        logits = await self.engine.classify(image)
        return {"class": int(np.argmax(logits)), "logits": np.asarray(logits).tolist()}

    async def Health(self, request, context):
        return self.engine.health_check()


def add_inference_service(server, servicer: InferenceServicer, container=None) -> None:
    handler = json_method_handlers(
        SERVICE,
        unary={
            "Generate": servicer.Generate,
            "Embed": servicer.Embed,
            "Classify": servicer.Classify,
            "Health": servicer.Health,
        },
        streams={"GenerateStream": servicer.GenerateStream},
    )
    server.add_generic_rpc_handlers((handler,))


class InferenceClient:
    """Minimal sync client for the JSON inference service (tests/bench)."""

    def __init__(self, address: str) -> None:
        self._channel = grpc.insecure_channel(address)

    def _unary(self, method: str, payload: dict) -> dict:
        fn = self._channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=lambda o: json.dumps(o).encode(),
            response_deserializer=lambda b: json.loads(b or b"{}"),
        )
        return fn(payload)

    def generate(self, prompt: str, **kw) -> dict:
        return self._unary("Generate", {"prompt": prompt, **kw})

    def generate_stream(self, prompt: str, **kw):
        fn = self._channel.unary_stream(
            f"/{SERVICE}/GenerateStream",
            request_serializer=lambda o: json.dumps(o).encode(),
            response_deserializer=lambda b: json.loads(b or b"{}"),
        )
        yield from fn({"prompt": prompt, **kw})

    def embed(self, text: str) -> dict:
        return self._unary("Embed", {"text": text})

    def classify(self, image) -> dict:
        return self._unary("Classify", {"image": np.asarray(image).tolist()})

    def health(self) -> dict:
        return self._unary("Health", {})

    def close(self) -> None:
        self._channel.close()
