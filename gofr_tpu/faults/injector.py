"""Deterministic fault injection for the serving core.

A :class:`FaultInjector` is a registry of *named injection points* —
the seams the resilience layer must survive — that production code
fires unconditionally and tests arm selectively:

* ``engine.submit``       — inside the submit path, before enqueue
* ``engine.tokenize``     — before the tokenizer encodes a prompt
* ``scheduler.window``    — top of every scheduler loop iteration
* ``scheduler.device_step`` — before a decode/prefill device dispatch
* ``http.request``        — in ``HTTPService.request`` before the wire:
  raise = connect-refused / transport loss; return a ``Response`` =
  canned upstream answer (5xx burst without a socket)
* ``http.stream.open``    — before an SSE stream connects: raise =
  connect-refused; return an iterable = serve the stream from it
* ``http.stream.event``   — per received SSE line: raise = mid-body
  connection reset; return ``"truncate"`` = upstream vanished without
  EOF framing (truncated SSE); a blocking action models a read stall
* ``tier.prefill_done``   — at the prefill→transfer boundary on a
  prefill-tier replica (scheduler, just after finalize): raise = the
  replica failing right as its prefill completes → local fused decode
* ``tier.transfer``       — per tier-transfer attempt in the pool:
  raise = the transfer leg dying mid-ship (retried with backoff, then
  fused fallback on a sibling)
* ``tier.import``         — in ``engine.handoff_prefilled`` on the
  decode replica: raise = the importer rejecting the shipped blocks
  (pool pressure / version mismatch)
* ``transfer.dma.offer``  — in ``DmaTransferServer.offer``
  (``service/dma.py``), before a payload stages for the dma leg:
  raise = the transfer server refusing/unreachable at export time —
  the ladder bans the dma rung and retries the same target via wire
* ``transfer.dma.fetch``  — in ``dma_fetch`` before the data socket
  opens (kwargs ``key``/``address``): raise = connect-refused/reset
  without a socket; raising ``DmaError(kind=...)`` picks the matrix
  row (connect / read / stale) deterministically
* ``transfer.dma.serve``  — server side, after the fetch key is read
  and before the reply frame (kwargs ``key``/``server``): a blocking
  action = a stalled exporter mid-transfer (slow-loris / partition) —
  the importer's read budget must cut the wait; the subprocess chaos
  suite parks a stall here then ``kill -9``s the exporter for the
  died-mid-DMA cell
* ``transfer.source.pull`` — in the pool's remote prefill-source pull
  (``replica_pool._source_prefill``), before the export request
  (kwargs ``source``/``mode``): raise = the source dying between
  discovery and pull — the request must fall back to local prefill
  with zero 5xx
* ``control.signal``      — per control-plane signal read
  (``serving/control_plane.py``; kwarg ``signal`` names it): raise =
  the sensor throwing; return ``"stale"`` = no fresh sample this pass;
  return a float (NaN included) = the sensor lying with that value.
  The control plane's guard must absorb every mode — last-good value,
  then observe-only — without a crash or a 5xx
* ``pubsub.deliver``      — in the async serving plane
  (``serving/async_serving.py``), after a request-topic lease before
  the payload is parsed/admitted (kwargs ``topic``/``message_id``/
  ``attempt``): raise = a broker read error or poison payload — the
  message must nack onto the jittered-backoff redelivery path, never
  be lost
* ``pubsub.publish``      — before a reply or dead-letter publish
  (kwargs ``topic``/``message_id``): raise = the broker rejecting the
  write; the request's lease must survive for redelivery (the reply
  is NOT recorded in the dedup ledger, so the retry republishes)
* ``pubsub.ack``          — before the request-topic ack: raise = the
  consumer dying between publish and ack; the lease expires, the
  broker redelivers, and the dedup ledger must swallow the replay
  without a second reply publish

Unarmed, ``fire`` is one dict read (the serving hot path pays nothing
measurable). Armed, a point either **raises** the configured exception
or **runs** a callable — the callable form is how tests simulate a
stalled device step without sleeping: the action blocks on a
``threading.Event`` the test controls, so every ordering is explicit.

Determinism rules this module enforces by design:

* no randomness — a fault fires on exact hit counts (``after`` skips,
  ``times`` bounds), never probabilistically;
* no timers — "slow" is modeled by test-controlled events, "expired"
  by injectable clocks (``serving/lifecycle.py``), never ``sleep``;
* re-arming a point replaces its registry entry WITHOUT touching an
  in-flight action from the previous arming — the chaos suite
  (``tests/test_supervisor.py``) relies on this to park a thread with a
  stall, then swap in the raise that kills its next iteration.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional


@dataclass
class _ArmedFault:
    point: str
    raises: Optional[BaseException] = None
    action: Optional[Callable[..., Any]] = None
    times: Optional[int] = None  # max fires; None = every hit
    after: int = 0  # skip the first `after` hits
    hits: int = 0
    fired: int = 0
    lock: threading.Lock = field(default_factory=threading.Lock)


class FaultInjector:
    """Thread-safe named-fault registry (one global default below)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._points: dict[str, _ArmedFault] = {}

    # -- arming (test side) --------------------------------------------

    def arm(
        self,
        point: str,
        *,
        raises: Optional[BaseException] = None,
        action: Optional[Callable[..., Any]] = None,
        times: Optional[int] = None,
        after: int = 0,
    ) -> _ArmedFault:
        """Arm ``point``. Exactly one of ``raises``/``action`` must be
        given. ``times`` bounds total fires; ``after`` skips the first N
        hits (e.g. fail the *second* window only)."""
        if (raises is None) == (action is None):
            raise ValueError("arm() needs exactly one of raises= or action=")
        fault = _ArmedFault(
            point=point, raises=raises, action=action, times=times,
            after=after,
        )
        with self._lock:
            self._points[point] = fault
        return fault

    def disarm(self, point: str) -> None:
        with self._lock:
            self._points.pop(point, None)

    def reset(self) -> None:
        with self._lock:
            self._points.clear()

    @contextmanager
    def armed(
        self,
        point: str,
        *,
        raises: Optional[BaseException] = None,
        action: Optional[Callable[..., Any]] = None,
        times: Optional[int] = None,
        after: int = 0,
    ) -> Iterator[_ArmedFault]:
        """``with faults.armed("scheduler.device_step", raises=exc): ...``"""
        fault = self.arm(
            point, raises=raises, action=action, times=times, after=after
        )
        try:
            yield fault
        finally:
            self.disarm(point)

    def fired(self, point: str) -> int:
        """How many times ``point`` actually fired (0 if never armed)."""
        with self._lock:
            fault = self._points.get(point)
        return fault.fired if fault is not None else 0

    # -- firing (production side) --------------------------------------

    def fire(self, point: str, **ctx: Any) -> Any:
        """Called at the injection point. No-op unless armed; armed, it
        raises the configured exception or returns the action's result
        (the action receives ``ctx`` as keyword arguments)."""
        if not self._points:  # fast path: nothing armed anywhere
            return None
        fault = self._points.get(point)
        if fault is None:
            return None
        with fault.lock:
            fault.hits += 1
            if fault.hits <= fault.after:
                return None
            if fault.times is not None and fault.fired >= fault.times:
                return None
            fault.fired += 1
        if fault.action is not None:
            return fault.action(**ctx)
        assert fault.raises is not None
        raise fault.raises


#: Process-wide default injector: production seams fire on it, tests
#: arm it (and MUST disarm — use the ``armed`` context manager).
default_injector = FaultInjector()

fire = default_injector.fire
armed = default_injector.armed
arm = default_injector.arm
disarm = default_injector.disarm
reset = default_injector.reset
fired = default_injector.fired
