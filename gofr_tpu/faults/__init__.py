"""Deterministic fault-injection harness (``gofr_tpu.faults``).

Named injection points at the serving core's failure seams — device
step raises, stalled step, tokenizer failure, submit-path exception —
armed per-test so every resilience behavior is exercised without a TPU
and without sleeps. See ``injector.py`` for the point catalog and
``docs/advanced-guide/resilience.md`` for usage.
"""

from gofr_tpu.faults.injector import (
    FaultInjector,
    arm,
    armed,
    default_injector,
    disarm,
    fire,
    fired,
    reset,
)

__all__ = [
    "FaultInjector",
    "arm",
    "armed",
    "default_injector",
    "disarm",
    "fire",
    "fired",
    "reset",
]
