"""Named model registry (the serving engine resolves ``TPU_MODEL`` here).

Entries bundle a config with init/apply functions so the engine and bench
code are model-agnostic. Sizes: ``*-tiny`` for tests/compile checks,
``llama-1b`` fits a single v5e chip in bf16 for benchmarking, ``llama-3-8b``
is the flagship target config (BASELINE.json config 5).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax.numpy as jnp

from gofr_tpu.models.transformer import TransformerConfig


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    name: str
    family: str  # "llm" | "encoder" | "vision"
    config: Any
    init: Callable
    eos_token: int = 2
    # Per-model forward (vision family): fn(params, inputs, cfg) → logits.
    # LLM/encoder paths are architecture-generic and ignore this.
    forward: Any = None

    def describe(self) -> dict:
        return {"name": self.name, "family": self.family}


_REGISTRY: dict[str, ModelSpec] = {}


def register_model(spec: ModelSpec) -> None:
    _REGISTRY[spec.name] = spec


def get_model(name: str) -> ModelSpec:
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; known: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[name]


def list_models() -> list[str]:
    return sorted(_REGISTRY)


def _register_llms() -> None:
    from gofr_tpu.models.transformer import init_transformer

    llm_configs = {
        # Flagship target: Llama-3-8B dims (BASELINE.json config 5).
        "llama-3-8b": TransformerConfig(
            vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, max_len=8192, rope_theta=500000.0,
        ),
        # Multi-host scale target: Llama-3-70B dims — serves tp=8 per
        # v5e-8 slice (tp is capped by the 8 kv heads the cache shards
        # over); scale FURTHER with dp replicas / pp stages across hosts
        # via the DCN runtime (parallel/dcn.py). Capacity math in
        # tests/test_models.py.
        "llama-3-70b": TransformerConfig(
            vocab_size=128256, d_model=8192, n_layers=80, n_heads=64,
            n_kv_heads=8, d_ff=28672, max_len=8192, rope_theta=500000.0,
        ),
        # Mixtral-8x7B (MoE: 8 experts, top-2; 47B params total, ~13B
        # active). Serves tp-sharded — experts shard over the tp axis
        # (expert parallelism rides the model axis,
        # models/transformer.py transformer_param_specs); int4+tp2 or
        # int8+tp4 fit v5e slices. HF loader maps
        # block_sparse_moe.{gate,experts.*.w1/w2/w3}.
        "mixtral-8x7b": TransformerConfig(
            vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, max_len=8192, rope_theta=1e6,
            n_experts=8, n_experts_active=2,
        ),
        # Mistral-7B dims (HF loader accepts model_type=mistral):
        # sliding-window attention — every token attends the last 4096
        # positions, so max_len can exceed the window (the cache stores
        # max_len positions; the window is a masking contract).
        "mistral-7b": TransformerConfig(
            vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, max_len=8192, rope_theta=10000.0,
            sliding_window=4096,
        ),
        # Qwen2-7B dims (HF loader accepts model_type=qwen2; QKV bias).
        "qwen2-7b": TransformerConfig(
            vocab_size=152064, d_model=3584, n_layers=28, n_heads=28,
            n_kv_heads=4, d_ff=18944, max_len=8192, rope_theta=1e6,
            attn_bias=True,
        ),
        # Gemma-7B dims (HF loader accepts model_type=gemma): GeGLU FFN,
        # (1+w) RMSNorm, sqrt(d_model)-scaled tied embeddings, and an
        # explicit head_dim 256 (n_heads*head_dim = 4096 ≠ d_model 3072).
        "gemma-7b": TransformerConfig(
            vocab_size=256000, d_model=3072, n_layers=28, n_heads=16,
            n_kv_heads=16, d_ff=24576, max_len=8192, rope_theta=10000.0,
            norm_eps=1e-6, head_dim_override=256, act="gelu",
            norm_offset=True, embed_scale=True,
        ),
        # Gemma-2B: MQA (1 kv head), head_dim 256.
        "gemma-2b": TransformerConfig(
            vocab_size=256000, d_model=2048, n_layers=18, n_heads=8,
            n_kv_heads=1, d_ff=16384, max_len=8192, rope_theta=10000.0,
            norm_eps=1e-6, head_dim_override=256, act="gelu",
            norm_offset=True, embed_scale=True,
        ),
        # ~1.1B config that fits one v5e chip comfortably for benching.
        "llama-1b": TransformerConfig(
            vocab_size=32768, d_model=2048, n_layers=22, n_heads=16,
            n_kv_heads=4, d_ff=5632, max_len=4096, rope_theta=500000.0,
        ),
        # Test-size models (fast CPU compile).
        "llama-tiny": TransformerConfig(
            vocab_size=512, d_model=128, n_layers=2, n_heads=4,
            n_kv_heads=2, d_ff=256, max_len=256, rope_theta=10000.0,
        ),
        # f32 twin: the exact-comparison oracle for tests where bf16
        # argmax tie-breaks differ between execution shapes (e.g.
        # speculative verify [S, G+1] vs decode [S] forwards).
        "llama-tiny-f32": TransformerConfig(
            vocab_size=512, d_model=128, n_layers=2, n_heads=4,
            n_kv_heads=2, d_ff=256, max_len=256, rope_theta=10000.0,
            dtype=jnp.float32,
        ),
        "moe-tiny": TransformerConfig(
            vocab_size=512, d_model=128, n_layers=2, n_heads=4,
            n_kv_heads=2, d_ff=256, max_len=256, rope_theta=10000.0,
            n_experts=4, n_experts_active=2,
        ),
        # Pythia-6.9B dims (HF loader accepts model_type=gpt_neox):
        # LayerNorm+bias, parallel residual, partial rotary (25% of
        # head_dim), non-gated erf-gelu MLP, biases on every projection.
        "pythia-6.9b": TransformerConfig(
            vocab_size=50432, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=32, d_ff=16384, max_len=2048, rope_theta=10000.0,
            norm_eps=1e-5, norm="ln", parallel_residual=True,
            rotary_pct=0.25, ffn="mlp", act="gelu_exact", attn_bias=True,
            proj_bias=True,
        ),
        # GPT-2 (124M) dims (HF loader accepts model_type=gpt2):
        # learned positions, LayerNorm+bias, tanh-gelu MLP, tied head.
        "gpt2": TransformerConfig(
            vocab_size=50257, d_model=768, n_layers=12, n_heads=12,
            n_kv_heads=12, d_ff=3072, max_len=1024, norm="ln",
            ffn="mlp", act="gelu", attn_bias=True, proj_bias=True,
            pos_emb="learned",
        ),
        # GPT-2-arch test size (learned positions).
        "gpt2-tiny": TransformerConfig(
            vocab_size=512, d_model=128, n_layers=2, n_heads=4,
            n_kv_heads=4, d_ff=256, max_len=256, norm="ln",
            ffn="mlp", act="gelu", attn_bias=True, proj_bias=True,
            pos_emb="learned",
        ),
        # GPT-NeoX-arch test size.
        "neox-tiny": TransformerConfig(
            vocab_size=512, d_model=128, n_layers=2, n_heads=4,
            n_kv_heads=4, d_ff=256, max_len=256, rope_theta=10000.0,
            norm="ln", parallel_residual=True, rotary_pct=0.25,
            ffn="mlp", act="gelu_exact", attn_bias=True, proj_bias=True,
        ),
        # Gemma-arch test size: exercises head_dim override (64 ≠ 128/4),
        # GeGLU, (1+w) norms, and scaled embeddings on the fast CPU path.
        "gemma-tiny": TransformerConfig(
            vocab_size=512, d_model=128, n_layers=2, n_heads=4,
            n_kv_heads=2, d_ff=256, max_len=256, rope_theta=10000.0,
            norm_eps=1e-6, head_dim_override=64, act="gelu",
            norm_offset=True, embed_scale=True,
        ),
    }
    eos_tokens = {"gemma-7b": 1, "gemma-2b": 1, "gemma-tiny": 1,
                  "pythia-6.9b": 0, "neox-tiny": 0,
                  "gpt2": 50256, "gpt2-tiny": 0}
    for name, cfg in llm_configs.items():
        register_model(
            ModelSpec(
                name=name, family="llm", config=cfg, init=init_transformer,
                eos_token=eos_tokens.get(name, 2),
            )
        )


def _register_encoders() -> None:
    from gofr_tpu.models.bert import BertConfig, init_bert

    register_model(
        ModelSpec(
            name="bert-base",
            family="encoder",
            config=BertConfig(),
            init=init_bert,
        )
    )
    register_model(
        ModelSpec(
            name="bert-tiny",
            family="encoder",
            config=BertConfig(
                vocab_size=512, d_model=128, n_layers=2, n_heads=4, d_ff=256,
                max_len=128,
            ),
            init=init_bert,
        )
    )


def _register_seq2seq() -> None:
    from gofr_tpu.models.t5 import T5Config, init_t5

    register_model(
        ModelSpec(
            name="flan-t5-small",
            family="seq2seq",
            # t5-v1.1-small / flan-t5-small dims: gated-gelu, untied head.
            config=T5Config(
                d_model=512, d_kv=64, n_heads=6, n_layers=8, d_ff=1024,
            ),
            init=init_t5,
            eos_token=1,
        )
    )
    register_model(
        ModelSpec(
            name="t5-tiny",
            family="seq2seq",
            config=T5Config(
                vocab_size=512, d_model=64, d_kv=16, n_heads=4,
                n_layers=2, d_ff=128, max_len=128,
            ),
            init=init_t5,
            eos_token=1,
        )
    )


def _register_vision() -> None:
    from gofr_tpu.models.resnet import ResNetConfig, init_resnet, resnet_forward

    register_model(
        ModelSpec(
            name="resnet-50",
            family="vision",
            config=ResNetConfig(),
            init=init_resnet,
            forward=resnet_forward,
        )
    )
    from gofr_tpu.models.vit import ViTConfig, init_vit, vit_forward

    register_model(
        ModelSpec(
            name="vit-base",
            family="vision",
            config=ViTConfig(),
            init=init_vit,
            forward=vit_forward,
        )
    )
    register_model(
        ModelSpec(
            name="vit-tiny",
            family="vision",
            config=ViTConfig(
                image_size=32, patch_size=8, d_model=64, n_layers=2,
                n_heads=4, d_ff=128, num_classes=10,
            ),
            init=init_vit,
            forward=vit_forward,
        )
    )
    register_model(
        ModelSpec(
            name="resnet-tiny",
            family="vision",
            config=ResNetConfig(stage_sizes=(1, 1, 1, 1), width=16, num_classes=10),
            init=init_resnet,
            forward=resnet_forward,
        )
    )


_register_llms()
_register_encoders()
_register_seq2seq()
_register_vision()
