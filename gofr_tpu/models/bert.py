"""BERT-style bidirectional encoder (BASELINE.json config 3: embedding
endpoint over gRPC with dynamic batching).

Pure-JAX, scan-over-layers, bf16 with f32 softmax/pooling. ``bert_embed``
returns mean-pooled, L2-normalised sentence embeddings — the serving payload
for the embedding endpoint.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from gofr_tpu.ops.attention import attention
from gofr_tpu.ops.norms import layer_norm


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_len: int = 512
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_bert(key: jax.Array, cfg: BertConfig) -> dict:
    D, H, hd, F, L = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff, cfg.n_layers
    ks = jax.random.split(key, 10)

    def dense(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, dtype=jnp.float32) * fan_in**-0.5
        ).astype(cfg.dtype)

    return {
        "tok_embed": dense(ks[0], (cfg.vocab_size, D), D),
        "pos_embed": dense(ks[1], (cfg.max_len, D), D),
        "embed_norm_w": jnp.ones((D,), dtype=cfg.dtype),
        "embed_norm_b": jnp.zeros((D,), dtype=cfg.dtype),
        "layers": {
            "wq": dense(ks[2], (L, D, H * hd), D),
            "wk": dense(ks[3], (L, D, H * hd), D),
            "wv": dense(ks[4], (L, D, H * hd), D),
            "wo": dense(ks[5], (L, H * hd, D), D),
            "w_in": dense(ks[6], (L, D, F), D),
            "w_out": dense(ks[7], (L, F, D), F),
            "norm1_w": jnp.ones((L, D), dtype=cfg.dtype),
            "norm1_b": jnp.zeros((L, D), dtype=cfg.dtype),
            "norm2_w": jnp.ones((L, D), dtype=cfg.dtype),
            "norm2_b": jnp.zeros((L, D), dtype=cfg.dtype),
        },
    }


def bert_param_specs(cfg: BertConfig) -> dict:
    return {
        "tok_embed": P("tp", None),
        "pos_embed": P(None, None),
        "embed_norm_w": P(None),
        "embed_norm_b": P(None),
        "layers": {
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "w_in": P(None, None, "tp"),
            "w_out": P(None, "tp", None),
            "norm1_w": P(None, None),
            "norm1_b": P(None, None),
            "norm2_w": P(None, None),
            "norm2_b": P(None, None),
        },
    }


@partial(jax.jit, static_argnames=("cfg",))
def bert_forward(
    params: dict, tokens: jnp.ndarray, mask: jnp.ndarray, cfg: BertConfig
) -> jnp.ndarray:
    """tokens, mask: [b, s] (mask 1 = real token) → hidden states [b, s, D]."""
    b, s = tokens.shape
    H, hd = cfg.n_heads, cfg.head_dim
    x = params["tok_embed"][tokens] + params["pos_embed"][None, :s]
    x = layer_norm(x, params["embed_norm_w"], params["embed_norm_b"], cfg.norm_eps)

    attn_mask = jnp.broadcast_to(mask[:, None, :].astype(bool), (b, s, s))

    def body(x, lp):
        h = jnp.einsum("bsd,dh->bsh", x, lp["wq"]).reshape(b, s, H, hd)
        k = jnp.einsum("bsd,dh->bsh", x, lp["wk"]).reshape(b, s, H, hd)
        v = jnp.einsum("bsd,dh->bsh", x, lp["wv"]).reshape(b, s, H, hd)
        a = attention(h, k, v, causal=False, mask=attn_mask)
        x = layer_norm(
            x + jnp.einsum("bsh,hd->bsd", a.reshape(b, s, H * hd), lp["wo"]),
            lp["norm1_w"],
            lp["norm1_b"],
            cfg.norm_eps,
        )
        ffn = jnp.einsum(
            "bsf,fd->bsd",
            jax.nn.gelu(jnp.einsum("bsd,df->bsf", x, lp["w_in"])),
            lp["w_out"],
        )
        x = layer_norm(x + ffn, lp["norm2_w"], lp["norm2_b"], cfg.norm_eps)
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return x


@partial(jax.jit, static_argnames=("cfg",))
def bert_embed(
    params: dict, tokens: jnp.ndarray, mask: jnp.ndarray, cfg: BertConfig
) -> jnp.ndarray:
    """Mean-pooled L2-normalised embeddings [b, D] in f32."""
    hidden = bert_forward(params, tokens, mask, cfg).astype(jnp.float32)
    m = mask[:, :, None].astype(jnp.float32)
    pooled = jnp.sum(hidden * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)
