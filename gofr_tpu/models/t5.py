"""T5 encoder-decoder (text-to-text) — the seq2seq architecture class
next to the decoder-only, encoder-only, and vision families.

T5 particulars honored for HF parity: RMSNorm without bias, **unscaled**
attention scores (T5 folds the 1/sqrt(d) into its initialization),
learned RELATIVE position bias added to the scores (one bucket table
per attention kind, owned by layer 0 and shared by all layers; none on
cross-attention), explicit per-head ``d_kv`` (not d_model/heads), a
gated-gelu FFN for the v1.1 lineage (plain relu for original T5), and
the tied-head logit scaling ``d_model**-0.5`` only when tied.

TPU-first shape: encoder and decoder layers are stacked and scanned;
generation is one jitted program over a fixed ``[b, 1+max_new]``
decoder buffer — each step re-attends the whole buffer with causal +
validity masking (static shapes; O(n²) over a short answer buffer
beats dynamic-shape recompiles). Serving runs it behind the same
DynamicBatcher the encoder/vision families use.

Reference analog: none (GoFr has no models).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from gofr_tpu.ops.norms import rms_norm
from gofr_tpu.models.transformer import _wein


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64  # per-head; T5 does NOT require d_model/n_heads
    n_heads: int = 8
    n_layers: int = 6  # encoder layers == decoder layers
    d_ff: int = 2048
    rel_buckets: int = 32
    rel_max_distance: int = 128
    norm_eps: float = 1e-6
    gated_ffn: bool = True  # v1.1 gated-gelu; False = original relu
    tied_head: bool = False  # v1.1 unties; tied scales logits by d^-0.5
    max_len: int = 512
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.d_kv


def _rel_bucket(
    rel_pos: jnp.ndarray, bidirectional: bool, num_buckets: int, max_dist: int
) -> jnp.ndarray:
    """HF T5 bucketing: exact small distances, log-spaced large ones."""
    ret = jnp.zeros_like(rel_pos)
    n = rel_pos
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n > 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = -jnp.minimum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / jnp.log(max_dist / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


def _rel_bias(
    table: jnp.ndarray, q_len: int, k_len: int, bidirectional: bool,
    cfg: T5Config,
) -> jnp.ndarray:
    """[buckets, heads] table → [1, heads, q_len, k_len] score bias."""
    ctx = jnp.arange(q_len)[:, None]
    mem = jnp.arange(k_len)[None, :]
    buckets = _rel_bucket(
        mem - ctx, bidirectional, cfg.rel_buckets, cfg.rel_max_distance
    )
    return table[buckets].transpose(2, 0, 1)[None].astype(jnp.float32)


def init_t5(key: jax.Array, cfg: T5Config) -> dict:
    def dense(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, dtype=jnp.float32) * fan_in**-0.5
        ).astype(cfg.dtype)

    D, H, hd, F, L = cfg.d_model, cfg.n_heads, cfg.d_kv, cfg.d_ff, cfg.n_layers
    ks = iter(jax.random.split(key, 64))

    def attn_leaves():
        return {
            "wq": dense(next(ks), (L, D, H * hd), D),
            "wk": dense(next(ks), (L, D, H * hd), D),
            "wv": dense(next(ks), (L, D, H * hd), D),
            "wo": dense(next(ks), (L, H * hd, D), H * hd),
        }

    def ffn_leaves():
        leaves = {
            "w_up": dense(next(ks), (L, D, F), D),
            "w_down": dense(next(ks), (L, F, D), F),
        }
        if cfg.gated_ffn:
            leaves["w_gate"] = dense(next(ks), (L, D, F), D)
        return leaves

    enc = {
        "ln1": jnp.ones((L, D), cfg.dtype),
        "ln2": jnp.ones((L, D), cfg.dtype),
        **{f"sa_{k}": v for k, v in attn_leaves().items()},
        **ffn_leaves(),
    }
    dec = {
        "ln1": jnp.ones((L, D), cfg.dtype),
        "ln2": jnp.ones((L, D), cfg.dtype),
        "ln3": jnp.ones((L, D), cfg.dtype),
        **{f"sa_{k}": v for k, v in attn_leaves().items()},
        **{f"ca_{k}": v for k, v in attn_leaves().items()},
        **ffn_leaves(),
    }
    params = {
        "embed": dense(next(ks), (cfg.vocab_size, D), D),
        "enc_rel_bias": dense(
            next(ks), (cfg.rel_buckets, H), cfg.rel_buckets
        ),
        "dec_rel_bias": dense(
            next(ks), (cfg.rel_buckets, H), cfg.rel_buckets
        ),
        "encoder": enc,
        "decoder": dec,
        "enc_norm": jnp.ones((D,), cfg.dtype),
        "dec_norm": jnp.ones((D,), cfg.dtype),
    }
    if not cfg.tied_head:
        params["lm_head"] = dense(next(ks), (D, cfg.vocab_size), D)
    return params


def _mha(h_q, h_kv, lp, pre, cfg, bias, mask):
    """Unscaled T5 attention. h_q: [b, s_q, D]; h_kv: [b, s_kv, D];
    bias: [1, H, s_q, s_kv] or None; mask: [b, 1, s_q, s_kv] bool or
    None."""
    b, s_q, _ = h_q.shape
    s_kv = h_kv.shape[1]
    H, hd = cfg.n_heads, cfg.d_kv
    q = _wein("bsd,dh->bsh", h_q, lp[pre + "wq"]).reshape(b, s_q, H, hd)
    k = _wein("bsd,dh->bsh", h_kv, lp[pre + "wk"]).reshape(b, s_kv, H, hd)
    v = _wein("bsd,dh->bsh", h_kv, lp[pre + "wv"]).reshape(b, s_kv, H, hd)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )  # NO 1/sqrt(d) scale — T5 convention
    if bias is not None:
        scores = scores + bias
    if mask is not None:
        scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(h_q.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, s_q, H * hd)
    return _wein("bsh,hd->bsd", out, lp[pre + "wo"])


def _ffn(h, lp, cfg):
    if cfg.gated_ffn:
        g = jax.nn.gelu(
            _wein("bsd,df->bsf", h, lp["w_gate"]), approximate=True
        )
        u = _wein("bsd,df->bsf", h, lp["w_up"])
        return _wein("bsf,fd->bsd", g * u, lp["w_down"])
    u = jax.nn.relu(_wein("bsd,df->bsf", h, lp["w_up"]))
    return _wein("bsf,fd->bsd", u, lp["w_down"])


def t5_encode(
    params: dict, tokens: jnp.ndarray, lengths: jnp.ndarray, cfg: T5Config
) -> jnp.ndarray:
    """tokens [b, s], lengths [b] → encoder states [b, s, D]."""
    b, s = tokens.shape
    x = params["embed"][tokens]
    bias = _rel_bias(params["enc_rel_bias"], s, s, True, cfg)
    key_ok = (jnp.arange(s)[None, :] < lengths[:, None])[:, None, None, :]

    def body(x, lp):
        h = rms_norm(x, lp["ln1"], cfg.norm_eps)
        x = x + _mha(h, h, lp, "sa_", cfg, bias, key_ok)
        h = rms_norm(x, lp["ln2"], cfg.norm_eps)
        return x + _ffn(h, lp, cfg), None

    x, _ = jax.lax.scan(body, x, params["encoder"])
    return rms_norm(x, params["enc_norm"], cfg.norm_eps)


def t5_decode(
    params: dict,
    dec_tokens: jnp.ndarray,
    enc_states: jnp.ndarray,
    enc_lengths: jnp.ndarray,
    cfg: T5Config,
    dec_lengths: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """dec_tokens [b, t] (starts with pad=0, the T5 BOS) → logits
    [b, t, vocab] f32. dec_lengths masks decoder self-attention keys
    beyond the valid prefix (generation's fixed buffer)."""
    b, t = dec_tokens.shape
    s = enc_states.shape[1]
    x = params["embed"][dec_tokens]
    bias = _rel_bias(params["dec_rel_bias"], t, t, False, cfg)
    causal = (
        jnp.arange(t)[None, :] <= jnp.arange(t)[:, None]
    )[None, None]  # [1, 1, t, t]
    self_mask = causal
    if dec_lengths is not None:
        self_mask = self_mask & (
            jnp.arange(t)[None, :] < dec_lengths[:, None]
        )[:, None, None, :]
    cross_mask = (
        jnp.arange(s)[None, :] < enc_lengths[:, None]
    )[:, None, None, :]

    def body(x, lp):
        h = rms_norm(x, lp["ln1"], cfg.norm_eps)
        x = x + _mha(h, h, lp, "sa_", cfg, bias, self_mask)
        h = rms_norm(x, lp["ln2"], cfg.norm_eps)
        x = x + _mha(h, enc_states, lp, "ca_", cfg, None, cross_mask)
        h = rms_norm(x, lp["ln3"], cfg.norm_eps)
        return x + _ffn(h, lp, cfg), None

    x, _ = jax.lax.scan(body, x, params["decoder"])
    x = rms_norm(x, params["dec_norm"], cfg.norm_eps)
    if cfg.tied_head:
        x = x * (cfg.d_model**-0.5)
        head = jnp.swapaxes(params["embed"], 0, 1)
    else:
        head = params["lm_head"]
    return _wein("btd,dv->btv", x, head).astype(jnp.float32)


def t5_generate(
    params: dict,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    cfg: T5Config,
    max_new: int = 32,
    eos_id: int = 1,
) -> jnp.ndarray:
    """Batched greedy generation: tokens [b, s] + lengths [b] →
    generated ids [b, max_new] (entries after EOS are pad=0).

    One jitted program: encode once, then a ``lax.scan`` over a fixed
    ``[b, 1+max_new]`` decoder buffer — step i re-runs the decoder over
    the buffer with validity masking and writes position i+1. Static
    shapes throughout; the quadratic recompute over a short answer
    buffer is the compile-friendly trade.
    """
    enc = t5_encode(params, tokens, lengths, cfg)
    b = tokens.shape[0]
    buf0 = jnp.zeros((b, 1 + max_new), dtype=jnp.int32)  # pos 0 = T5 BOS
    done0 = jnp.zeros((b,), dtype=bool)

    def step(carry, i):
        buf, done = carry
        logits = t5_decode(
            params, buf, enc, lengths, cfg,
            dec_lengths=jnp.full((b,), i + 1, jnp.int32),
        )
        nxt = jnp.argmax(logits[:, i], axis=-1).astype(jnp.int32)
        nxt = jnp.where(done, 0, nxt)
        buf = buf.at[:, i + 1].set(nxt)
        done = done | (nxt == eos_id)
        return (buf, done), None

    (buf, _), _ = jax.lax.scan(
        step, (buf0, done0), jnp.arange(max_new)
    )
    return buf[:, 1:]


def t5_generate_chunk(
    params: dict,
    buf: jnp.ndarray,
    done: jnp.ndarray,
    enc_states: jnp.ndarray,
    enc_lengths: jnp.ndarray,
    start: jnp.ndarray,
    cfg: T5Config,
    chunk: int,
    eos_id: int = 1,
) -> tuple:
    """Advance the fixed ``[b, 1+N]`` answer buffer by ``chunk`` greedy
    steps from dynamic position ``start`` — the stepped-decode dispatch
    unit behind STREAMING seq2seq (same shape discipline as the LLM
    decode windows: static shapes, traced start index, host fetch per
    chunk). Greedy picks are identical to ``t5_generate``: both re-run
    the decoder over the buffer with the same validity masking, and
    positions beyond ``dec_lengths`` are masked, so buffer length does
    not affect the logits. Returns ``(buf, done)``.
    """
    b = buf.shape[0]

    def step(carry, j):
        buf, done = carry
        i = start + j
        logits = t5_decode(
            params, buf, enc_states, enc_lengths, cfg,
            dec_lengths=jnp.full((b,), i + 1, jnp.int32),
        )
        nxt = jnp.argmax(logits[:, i], axis=-1).astype(jnp.int32)
        nxt = jnp.where(done, 0, nxt)
        buf = buf.at[:, i + 1].set(nxt)
        done = done | (nxt == eos_id)
        return (buf, done), None

    (buf, done), _ = jax.lax.scan(step, (buf, done), jnp.arange(chunk))
    return buf, done


def config_from_hf_t5(path: str) -> T5Config:
    """Build a T5Config from an HF t5/flan-t5 ``config.json``."""
    import json
    import os

    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    if hf.get("model_type") != "t5":
        raise ValueError(f"not a t5 checkpoint: {hf.get('model_type')!r}")
    if hf.get("num_decoder_layers", hf["num_layers"]) != hf["num_layers"]:
        raise ValueError(
            "asymmetric encoder/decoder depths are not supported"
        )
    proj = hf.get("feed_forward_proj", "relu")
    if proj not in ("relu", "gated-gelu"):
        # 'gated-relu' / plain 'gelu' would silently run the wrong
        # activation in _ffn — reject loudly like unsupported model types.
        raise ValueError(f"unsupported t5 feed_forward_proj {proj!r}")
    return T5Config(
        vocab_size=hf["vocab_size"],
        d_model=hf["d_model"],
        d_kv=hf["d_kv"],
        n_heads=hf["num_heads"],
        n_layers=hf["num_layers"],
        d_ff=hf["d_ff"],
        rel_buckets=hf.get("relative_attention_num_buckets", 32),
        rel_max_distance=hf.get("relative_attention_max_distance", 128),
        norm_eps=float(hf.get("layer_norm_epsilon", 1e-6)),
        gated_ffn=proj.startswith("gated"),
        tied_head=bool(hf.get("tie_word_embeddings", True)),
    )


def load_hf_t5(
    path: str, cfg: T5Config | None = None, *, quant: str = ""
) -> dict:
    """Load an HF t5/flan-t5 safetensors checkpoint into the t5 pytree.

    Same conventions as the decoder loader (``serving/hf_loader``): HF
    linears are [out, in] → transposed to [in, out]; per-layer tensors
    stack along the scan axis; the relative-attention bias tables live
    on block 0 only. ``gated_ffn`` maps wi_0→gate, wi_1→up; plain relu
    maps wi→up. ``quant`` ("int8"/"int4") quantizes each projection
    leaf AS IT LANDS — an 11B flan-t5-xxl must fit at its quantized
    footprint, never the full bf16 tree (the decoder-loader memory
    discipline).
    """
    import numpy as np

    from gofr_tpu.serving.hf_loader import _TensorSource

    file_cfg = config_from_hf_t5(path)
    if cfg is None:
        cfg = file_cfg
    else:
        for field in ("vocab_size", "d_model", "d_kv", "n_heads",
                      "n_layers", "d_ff", "rel_buckets",
                      "rel_max_distance", "gated_ffn", "tied_head"):
            want, have = getattr(cfg, field), getattr(file_cfg, field)
            if want != have:
                raise ValueError(
                    f"checkpoint/config mismatch: {field}={have} in "
                    f"{path}/config.json but engine expects {want}"
                )
    # Lazy per-leaf access (the hf_loader memory discipline: the full
    # tree never materializes twice on host).
    src = _TensorSource(path)
    if quant:
        from gofr_tpu.ops.quant import _quant_fn

        qleaf = jax.jit(_quant_fn(quant), donate_argnums=(0,))
    else:
        qleaf = None

    L = cfg.n_layers

    def stack(fmt: str, transpose: bool = True, quantize: bool = False):
        a = np.stack([np.asarray(src.get(fmt.format(i))) for i in range(L)])
        if transpose:
            a = np.swapaxes(a, -1, -2)
        out = jnp.asarray(a, cfg.dtype)
        if quantize and qleaf is not None:
            out = qleaf(out)
        return out

    def attn(side: str, layer_idx: int, pre: str) -> dict:
        base = f"{side}.block.{{}}.layer.{layer_idx}."
        kind = "SelfAttention" if layer_idx == 0 else "EncDecAttention"
        return {
            f"{pre}{w}": stack(base + kind + f".{h}.weight", quantize=True)
            for w, h in (("wq", "q"), ("wk", "k"), ("wv", "v"), ("wo", "o"))
        }

    def ffn(side: str, layer_idx: int) -> dict:
        base = f"{side}.block.{{}}.layer.{layer_idx}.DenseReluDense."
        if cfg.gated_ffn:
            return {
                "w_gate": stack(base + "wi_0.weight", quantize=True),
                "w_up": stack(base + "wi_1.weight", quantize=True),
                "w_down": stack(base + "wo.weight", quantize=True),
            }
        return {
            "w_up": stack(base + "wi.weight", quantize=True),
            "w_down": stack(base + "wo.weight", quantize=True),
        }

    enc = {
        "ln1": stack("encoder.block.{}.layer.0.layer_norm.weight", False),
        "ln2": stack("encoder.block.{}.layer.1.layer_norm.weight", False),
        **attn("encoder", 0, "sa_"),
        **ffn("encoder", 1),
    }
    dec = {
        "ln1": stack("decoder.block.{}.layer.0.layer_norm.weight", False),
        "ln2": stack("decoder.block.{}.layer.1.layer_norm.weight", False),
        "ln3": stack("decoder.block.{}.layer.2.layer_norm.weight", False),
        **attn("decoder", 0, "sa_"),
        **attn("decoder", 1, "ca_"),
        **ffn("decoder", 2),
    }
    params = {
        "embed": jnp.asarray(src.get("shared.weight"), cfg.dtype),
        "enc_rel_bias": jnp.asarray(src.get(
            "encoder.block.0.layer.0.SelfAttention"
            ".relative_attention_bias.weight"
        ), cfg.dtype),
        "dec_rel_bias": jnp.asarray(src.get(
            "decoder.block.0.layer.0.SelfAttention"
            ".relative_attention_bias.weight"
        ), cfg.dtype),
        "encoder": enc,
        "decoder": dec,
        "enc_norm": jnp.asarray(
            src.get("encoder.final_layer_norm.weight"), cfg.dtype
        ),
        "dec_norm": jnp.asarray(
            src.get("decoder.final_layer_norm.weight"), cfg.dtype
        ),
    }
    if not cfg.tied_head:
        head = jnp.asarray(
            np.swapaxes(np.asarray(src.get("lm_head.weight")), 0, 1),
            cfg.dtype,
        )
        # Read-before-donate ordering (graftlint GL007): the bare `head`
        # branch must evaluate before the donating qleaf call.
        params["lm_head"] = head if qleaf is None else qleaf(head)
    return params


def quantize_t5_params(params: dict, mode: str = "int8") -> dict:
    """Weight-only quantization of a T5 tree's matmul leaves (the
    sa_/ca_-prefixed projections and the FFN weights in both stacks,
    plus the untied lm_head). Norms, embeddings, and the relative-bias
    tables stay bf16 — _QUANT_KEYS is the ONE quantization-policy set
    shared with the decoder tree."""
    from gofr_tpu.ops.quant import _QUANT_KEYS, _quant_fn

    quant = _quant_fn(mode)

    def qsub(tree: dict) -> dict:
        return {
            k: quant(v)
            if k.removeprefix("sa_").removeprefix("ca_") in _QUANT_KEYS
            else v
            for k, v in tree.items()
        }

    out = dict(params)
    out["encoder"] = qsub(params["encoder"])
    out["decoder"] = qsub(params["decoder"])
    if "lm_head" in params:
        out["lm_head"] = quant(params["lm_head"])
    return out
