"""ResNet-50 (BASELINE.json config 2: image classification over HTTP POST).

Pure-JAX bottleneck ResNet in NHWC (TPU's native conv layout). Inference-mode
batch norm (folded scale/bias applied with stored moments) — the serving
path; training-mode BN is out of scope for an inference benchmark model.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16


def _conv_init(key, shape):
    fan_in = shape[0] * shape[1] * shape[2]
    return (jax.random.normal(key, shape, dtype=jnp.float32) * (2.0 / fan_in) ** 0.5)


def _bn_params(c):
    return {
        "scale": jnp.ones((c,), jnp.float32),
        "bias": jnp.zeros((c,), jnp.float32),
        "mean": jnp.zeros((c,), jnp.float32),
        "var": jnp.ones((c,), jnp.float32),
    }


def init_resnet(key: jax.Array, cfg: ResNetConfig) -> dict:
    keys = iter(jax.random.split(key, 256))
    params: dict = {
        "stem": {
            "conv": _conv_init(next(keys), (7, 7, 3, cfg.width)),
            "bn": _bn_params(cfg.width),
        },
        "stages": [],
    }
    in_ch = cfg.width
    for stage_idx, n_blocks in enumerate(cfg.stage_sizes):
        out_ch = cfg.width * (2**stage_idx) * 4
        mid_ch = cfg.width * (2**stage_idx)
        blocks = []
        for block_idx in range(n_blocks):
            block = {
                "conv1": _conv_init(next(keys), (1, 1, in_ch, mid_ch)),
                "bn1": _bn_params(mid_ch),
                "conv2": _conv_init(next(keys), (3, 3, mid_ch, mid_ch)),
                "bn2": _bn_params(mid_ch),
                "conv3": _conv_init(next(keys), (1, 1, mid_ch, out_ch)),
                "bn3": _bn_params(out_ch),
            }
            if block_idx == 0:
                block["proj"] = _conv_init(next(keys), (1, 1, in_ch, out_ch))
                block["proj_bn"] = _bn_params(out_ch)
            blocks.append(block)
            in_ch = out_ch
        params["stages"].append(blocks)
    params["head"] = {
        "w": (jax.random.normal(next(keys), (in_ch, cfg.num_classes)) * in_ch**-0.5),
        "b": jnp.zeros((cfg.num_classes,)),
    }
    return params


def _bn(x, p, eps=1e-5):
    inv = jax.lax.rsqrt(p["var"] + eps) * p["scale"]
    return x * inv + (p["bias"] - p["mean"] * inv)


def _conv(x, w, stride=1, padding="SAME"):
    return jax.lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)


def _bottleneck(x, block, stride):
    out = jax.nn.relu(_bn(_conv(x, block["conv1"]), block["bn1"]))
    out = jax.nn.relu(_bn(_conv(out, block["conv2"], stride=stride), block["bn2"]))
    out = _bn(_conv(out, block["conv3"]), block["bn3"])
    if "proj" in block:
        x = _bn(_conv(x, block["proj"], stride=stride), block["proj_bn"])
    return jax.nn.relu(out + x)


@partial(jax.jit, static_argnames=("cfg",))
def resnet_forward(params: dict, images: jnp.ndarray, cfg: ResNetConfig) -> jnp.ndarray:
    """images: [b, 224, 224, 3] (any HxW divisible by 32) → logits [b, classes]."""
    x = images.astype(cfg.dtype)
    x = _conv(x, params["stem"]["conv"], stride=2)
    x = jax.nn.relu(_bn(x, params["stem"]["bn"]))
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for stage_idx, blocks in enumerate(params["stages"]):
        for block_idx, block in enumerate(blocks):
            stride = 2 if (stage_idx > 0 and block_idx == 0) else 1
            x = _bottleneck(x, block, stride)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    return x @ params["head"]["w"] + params["head"]["b"]
