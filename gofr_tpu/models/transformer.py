"""Llama-family decoder-only transformer (flagship model).

TPU-first design decisions:

* **Scan over layers** — per-layer params are stacked along a leading axis
  and iterated with ``lax.scan``, so the program XLA compiles is one layer
  body regardless of depth (fast compiles, perfect for pjit);
* **bf16 params / f32 accumulation** — matmuls run on the MXU in bf16 with
  ``preferred_element_type=f32`` where it matters (attention softmax, loss);
* **GQA + RoPE + RMSNorm + SwiGLU** (Llama-3 architecture), optional
  **MoE** FFN (top-k routing over stacked experts) so expert parallelism is
  a first-class sharding axis;
* **Functional KV cache** threaded through prefill/decode (see
  ``gofr_tpu/ops/kv_cache.py``).

Partition specs for every param live next to the model
(:func:`transformer_param_specs`) keyed by logical mesh axes ``dp``/``tp``
— the scaling-book recipe: pick a mesh, annotate shardings, let XLA insert
the collectives.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gofr_tpu.ops.attention import (
    attention,
    cache_chunk_attention,
    decode_attention,
    verify_chunk_attention,
)
from gofr_tpu.ops.kv_cache import (
    KVCache,
    PagedKVCache,
    fake_quantize_kv,
    paged_view,
    quantize_kv,
)
from gofr_tpu.ops.norms import layer_norm, rms_norm
from gofr_tpu.ops.rotary import apply_rope, rope_frequencies


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 128256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # MoE: n_experts == 0 → dense SwiGLU FFN.
    n_experts: int = 0
    n_experts_active: int = 2
    # Qwen2-style QKV projection bias (llama/mistral/mixtral: False).
    attn_bias: bool = False
    # Gemma-family switches: explicit head_dim (Gemma-7B: 256 with
    # n_heads*head_dim != d_model), tanh-approximate GeGLU FFN, RMSNorm
    # computed as x/rms * (1 + w), and sqrt(d_model)-scaled embeddings.
    head_dim_override: int = 0
    act: str = "silu"  # "silu" | "gelu" | "gelu_exact"
    norm_offset: bool = False
    embed_scale: bool = False
    # GPT-NeoX/Pythia-family switches: LayerNorm (with bias) instead of
    # RMSNorm, x + attn(ln1 x) + mlp(ln2 x) parallel residual, partial
    # rotary (rope on the first rotary_pct of head_dim), a non-gated
    # act(x·W_up)·W_down MLP, and biases on every projection.
    norm: str = "rms"  # "rms" | "ln"
    parallel_residual: bool = False
    rotary_pct: float = 1.0
    ffn: str = "swiglu"  # "swiglu" | "mlp"
    proj_bias: bool = False  # wo/w_up/w_down biases (NeoX dense biases)
    # GPT-2: learned absolute position embeddings instead of RoPE (a
    # [max_len, d_model] table added at the embedding; rope is skipped).
    pos_emb: str = "rope"  # "rope" | "learned"
    # Mistral: sliding-window attention — every query attends only the
    # last `sliding_window` positions (0 = full causal). The cache still
    # stores max_len positions; the window is a masking contract, which
    # is what lets max_len exceed the window.
    sliding_window: int = 0

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.d_model // self.n_heads

    @property
    def rope_dims(self) -> int:
        nd = int(self.head_dim * self.rotary_pct)
        return nd - (nd % 2)  # rotate-half needs an even subspace

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_transformer(key: jax.Array, cfg: TransformerConfig) -> dict:
    """Random-init params as a pytree with stacked per-layer leaves."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def dense_init(key, shape, fan_in):
        scale = fan_in**-0.5
        return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(
            cfg.dtype
        )

    D, H, KV, hd, F, L = (
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.head_dim,
        cfg.d_ff,
        cfg.n_layers,
    )
    ks = jax.random.split(k_layers, 12)
    layers: dict[str, jnp.ndarray] = {
        "wq": dense_init(ks[0], (L, D, H * hd), D),
        "wk": dense_init(ks[1], (L, D, KV * hd), D),
        "wv": dense_init(ks[2], (L, D, KV * hd), D),
        "wo": dense_init(ks[3], (L, H * hd, D), H * hd),
        # norm_offset models (Gemma) store w with the +1 applied in the
        # forward, so identity init is zeros there, ones otherwise.
        "attn_norm": jnp.full((L, D), 0.0 if cfg.norm_offset else 1.0, cfg.dtype),
        "mlp_norm": jnp.full((L, D), 0.0 if cfg.norm_offset else 1.0, cfg.dtype),
    }
    if cfg.norm == "ln":
        layers.update(
            attn_norm_b=jnp.zeros((L, D), dtype=cfg.dtype),
            mlp_norm_b=jnp.zeros((L, D), dtype=cfg.dtype),
        )
    if cfg.proj_bias:
        layers.update(
            wo_b=jnp.zeros((L, D), dtype=cfg.dtype),
            w_up_b=jnp.zeros((L, F), dtype=cfg.dtype),
            w_down_b=jnp.zeros((L, D), dtype=cfg.dtype),
        )
    if cfg.attn_bias:
        layers.update(
            wq_b=jnp.zeros((L, H * hd), dtype=cfg.dtype),
            wk_b=jnp.zeros((L, KV * hd), dtype=cfg.dtype),
            wv_b=jnp.zeros((L, KV * hd), dtype=cfg.dtype),
        )
    if cfg.is_moe:
        E = cfg.n_experts
        layers.update(
            router=dense_init(ks[4], (L, D, E), D),
            w_gate=dense_init(ks[5], (L, E, D, F), D),
            w_up=dense_init(ks[6], (L, E, D, F), D),
            w_down=dense_init(ks[7], (L, E, F, D), F),
        )
    elif cfg.ffn == "mlp":
        layers.update(
            w_up=dense_init(ks[6], (L, D, F), D),
            w_down=dense_init(ks[7], (L, F, D), F),
        )
    else:
        layers.update(
            w_gate=dense_init(ks[5], (L, D, F), D),
            w_up=dense_init(ks[6], (L, D, F), D),
            w_down=dense_init(ks[7], (L, F, D), F),
        )
    out = {
        "embed": dense_init(k_embed, (cfg.vocab_size, D), D),
        "layers": layers,
        "final_norm": jnp.full(
            (D,), 0.0 if cfg.norm_offset else 1.0, cfg.dtype
        ),
        "lm_head": dense_init(k_head, (D, cfg.vocab_size), D),
    }
    if cfg.norm == "ln":
        out["final_norm_b"] = jnp.zeros((D,), dtype=cfg.dtype)
    if cfg.pos_emb == "learned":
        out["pos_embed"] = dense_init(
            jax.random.fold_in(k_embed, 1), (cfg.max_len, D), D
        )
    return out


def transformer_param_specs(cfg: TransformerConfig, pp: bool = False) -> dict:
    """PartitionSpecs over logical axes ('dp', 'tp', optionally 'pp') for
    every param leaf.

    Megatron-style: attention QKV column-parallel / O row-parallel over
    ``tp``; FFN gate/up column-parallel, down row-parallel; embeddings and
    lm_head vocab-parallel; norms replicated. MoE experts sharded over
    ``tp`` on the expert axis (expert parallelism rides the model axis).
    With ``pp`` the stacked layer axis (leading dim of every layer leaf)
    shards over the pipeline axis — each stage owns a contiguous slice of
    layers (see ``parallel/pipeline.py``).
    """
    lax_ = "pp" if pp else None  # leading (layer) axis of stacked leaves
    layers = {
        "wq": P(lax_, None, "tp"),
        "wk": P(lax_, None, "tp"),
        "wv": P(lax_, None, "tp"),
        "wo": P(lax_, "tp", None),
        "attn_norm": P(lax_, None),
        "mlp_norm": P(lax_, None),
    }
    if cfg.attn_bias:
        layers.update(
            wq_b=P(lax_, "tp"),
            wk_b=P(lax_, "tp"),
            wv_b=P(lax_, "tp"),
        )
    if cfg.norm == "ln":
        layers.update(attn_norm_b=P(lax_, None), mlp_norm_b=P(lax_, None))
    if cfg.proj_bias:
        # Row-parallel outputs (wo, w_down) have replicated biases; the
        # column-parallel up-projection bias shards with its outputs.
        layers.update(
            wo_b=P(lax_, None),
            w_up_b=P(lax_, "tp"),
            w_down_b=P(lax_, None),
        )
    if cfg.is_moe:
        layers.update(
            router=P(lax_, None, None),
            w_gate=P(lax_, "tp", None, None),
            w_up=P(lax_, "tp", None, None),
            w_down=P(lax_, "tp", None, None),
        )
    elif cfg.ffn == "mlp":
        layers.update(
            w_up=P(lax_, None, "tp"),
            w_down=P(lax_, "tp", None),
        )
    else:
        layers.update(
            w_gate=P(lax_, None, "tp"),
            w_up=P(lax_, None, "tp"),
            w_down=P(lax_, "tp", None),
        )
    out = {
        "embed": P("tp", None),
        "layers": layers,
        "final_norm": P(None),
        "lm_head": P(None, "tp"),
    }
    if cfg.norm == "ln":
        out["final_norm_b"] = P(None)
    if cfg.pos_emb == "learned":
        out["pos_embed"] = P(None, None)
    return out


def kv_cache_specs(
    quantized: bool = False, paged: bool = False, cp: bool = False
):
    """Cache layout [L, slots|blocks, kv_heads, len|block, hd]: kv_heads
    over ``tp``. Int8 mode adds per-position scales whose kv_heads axis
    shards the same way; the paged pool shards identically (axis 2) with
    a replicated block table.

    ``cp`` (serving context parallelism): the LENGTH axis additionally
    shards over the ``cp`` mesh axis — each chip holds a slice of every
    sequence and GSPMD partitions the dense decode/prefill attention
    (sharded softmax reductions become collectives). This is what lets
    max_len exceed one chip's cache HBM. Not combinable with paging.
    """
    seq = "cp" if cp else None
    kv = P(None, None, "tp", seq, None)
    if paged:
        if cp:
            raise ValueError("paged cache and cp sharding are exclusive")
        return PagedKVCache(
            k=kv,
            v=kv,
            block_table=P(None, None),
            lengths=P(None),
            k_s=kv if quantized else None,
            v_s=kv if quantized else None,
        )
    scale = P(None, None, "tp", None, seq)
    return KVCache(
        k=kv,
        v=kv,
        lengths=P(None),
        k_s=scale if quantized else None,
        v_s=scale if quantized else None,
    )


# ---------------------------------------------------------------------------
# layer body (shared by train/prefill/decode)
# ---------------------------------------------------------------------------


def _wein(subscripts, x, w):
    """einsum whose weight operand may be int8-quantized (ops/quant.Q8).

    Per-output-channel scales commute with the contraction (every Q8
    scale reduces the -2 axis, the one every ``_wein`` call contracts),
    so dequant is applied to the OUTPUT: ``(x · q) * s``. The weight
    operand then carries only an int8→bf16 convert — which XLA can fuse
    into the matmul's operand read — instead of a convert+multiply that
    risks materializing a full bf16 weight copy in HBM each decode step.
    The cast is exact (|q| ≤ 127 is representable in bf16).

    Every call site contracts w's -2 axis and keeps w's remaining dims
    as the output's trailing dims, so ``squeeze(s, -2)`` broadcasts onto
    the output directly (checked for dense, stacked, MoE, and lm_head
    shapes).
    """
    from gofr_tpu.ops.quant import Q4, Q8, dequantize

    if isinstance(w, Q8):
        out = jnp.einsum(subscripts, x, w.q.astype(x.dtype))
        return (out * jnp.squeeze(w.s, -2).astype(jnp.float32)).astype(x.dtype)
    if isinstance(w, Q4):
        # Group-wise scales don't commute with the full contraction, so
        # Q4 dequantizes the operand (int4 → bf16 × group scale); XLA
        # fuses or materializes per its cost model — the int4 HBM
        # footprint win holds either way.
        return jnp.einsum(subscripts, x, dequantize(w, x.dtype))
    return jnp.einsum(subscripts, x, w)


# ---------------------------------------------------------------------------
# multi-LoRA (batched per-slot adapters)
# ---------------------------------------------------------------------------

# Projections LoRA can target (MoE expert weights excluded: per-token
# routing × per-slot adapters would need a double gather; out of scope).
LORA_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def lora_dims(cfg: TransformerConfig, target: str) -> tuple[int, int]:
    """(d_in, d_out) of a LoRA-targetable projection."""
    D, F = cfg.d_model, cfg.d_ff
    H, KV, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    return {
        "wq": (D, H * hd),
        "wk": (D, KV * hd),
        "wv": (D, KV * hd),
        "wo": (H * hd, D),
        "w_gate": (D, F),
        "w_up": (D, F),
        "w_down": (F, D),
    }[target]


def init_lora(
    cfg: TransformerConfig,
    n_adapters: int,
    rank: int,
    targets: tuple[str, ...] = ("wq", "wk", "wv", "wo"),
) -> dict:
    """Zero LoRA leaves to merge into ``params["layers"]``.

    Layout ``{t}_lora_a: [L, N, d_in, r]`` / ``{t}_lora_b: [L, N, r,
    d_out]`` — layer-major so the leaves ride the existing ``lax.scan``
    over ``params["layers"]`` (each step sees the per-layer [N, ...]
    slice), adapter-slot second so a per-row gather ``a[aids]`` batches
    every live adapter into one einsum. All-zero init makes every
    adapter slot — and in particular slot 0, which requests without an
    adapter use — an exact no-op on the base model.
    """
    if cfg.is_moe:
        raise ValueError("LoRA serving does not support MoE models")
    leaves = {}
    for t in targets:
        if t not in LORA_TARGETS:
            raise ValueError(f"unknown LoRA target {t!r} (of {LORA_TARGETS})")
        d_in, d_out = lora_dims(cfg, t)
        leaves[t + "_lora_a"] = jnp.zeros(
            (cfg.n_layers, n_adapters, d_in, rank), dtype=cfg.dtype
        )
        leaves[t + "_lora_b"] = jnp.zeros(
            (cfg.n_layers, n_adapters, rank, d_out), dtype=cfg.dtype
        )
    return leaves


def lora_param_specs(
    targets: tuple[str, ...], pp: bool = False
) -> dict:
    """PartitionSpecs for the stacked LoRA leaves, matching the base
    projection's Megatron sharding: column-parallel targets shard B's
    output axis over ``tp`` (delta lands tp-sharded like the base
    output); row-parallel targets (wo, w_down) shard A's input axis so
    the rank-space contraction partial-sums over tp exactly where the
    base matmul does. Rank axes stay replicated (r is tiny)."""
    lax_ = "pp" if pp else None
    specs = {}
    for t in targets:
        if t in ("wo", "w_down"):
            specs[t + "_lora_a"] = P(lax_, None, "tp", None)
            specs[t + "_lora_b"] = P(lax_, None, None, None)
        else:
            specs[t + "_lora_a"] = P(lax_, None, None, None)
            specs[t + "_lora_b"] = P(lax_, None, None, "tp")
    return specs


def _lora(x, lp, name, aids):
    """Per-row LoRA delta for projection ``name``; 0.0 when the engine
    compiled without adapters (leaf absent — trace-time static) or the
    caller has no adapter plane. x rows map 1:1 onto ``aids`` entries;
    the rank-space bottleneck keeps the gathered [rows, d, r] operands
    small."""
    ka = name + "_lora_a"
    if aids is None or ka not in lp:
        return 0.0
    a = lp[ka][aids]  # [rows, d_in, r]
    b = lp[name + "_lora_b"][aids]  # [rows, r, d_out]
    if x.ndim == 2:
        xa = jnp.einsum("sd,sdr->sr", x, a)
        return jnp.einsum("sr,sro->so", xa, b)
    xa = jnp.einsum("btd,bdr->btr", x, a)
    return jnp.einsum("btr,bro->bto", xa, b)


def _act(cfg):
    """FFN activation — silu (Llama/SwiGLU), tanh-approximate gelu
    (Gemma/GeGLU), or erf gelu (GPT-NeoX); static per config, so each
    compiles its own program."""
    if cfg.act == "gelu":
        return partial(jax.nn.gelu, approximate=True)
    if cfg.act == "gelu_exact":
        return partial(jax.nn.gelu, approximate=False)
    return jax.nn.silu


def _norm(x, w, cfg, b=None):
    if cfg.norm == "ln":
        return layer_norm(x, w, b, cfg.norm_eps)
    return rms_norm(x, w, cfg.norm_eps, 1.0 if cfg.norm_offset else 0.0)


def _embed(params, tokens, cfg, positions=None):
    """Token embedding lookup; Gemma scales by sqrt(d_model) — the scalar
    is cast to the activation dtype first (HF casts the normalizer to the
    hidden dtype, and bf16 parity needs the same rounding). Learned
    position embeddings (GPT-2) add the position table here; rope models
    ignore ``positions``."""
    x = params["embed"][tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.d_model**0.5, dtype=x.dtype)
    if cfg.pos_emb == "learned":
        pos = jnp.clip(positions, 0, params["pos_embed"].shape[0] - 1)
        x = x + params["pos_embed"][pos]
    return x


def _ffn_dense(x, lp, cfg, aids=None):
    if cfg.ffn == "mlp":
        # Non-gated act(x·W_up + b)·W_down + b (GPT-NeoX/GPT-2 shape).
        h = _wein("bsd,df->bsf", x, lp["w_up"]) + _lora(x, lp, "w_up", aids)
        if "w_up_b" in lp:
            h = h + lp["w_up_b"]
        h = _act(cfg)(h)
        out = _wein("bsf,fd->bsd", h, lp["w_down"]) + _lora(
            h, lp, "w_down", aids
        )
        if "w_down_b" in lp:
            out = out + lp["w_down_b"]
        return out
    gate = _wein("bsd,df->bsf", x, lp["w_gate"]) + _lora(x, lp, "w_gate", aids)
    up = _wein("bsd,df->bsf", x, lp["w_up"]) + _lora(x, lp, "w_up", aids)
    h = _act(cfg)(gate) * up
    return _wein("bsf,fd->bsd", h, lp["w_down"]) + _lora(h, lp, "w_down", aids)


def _ffn_moe(x, lp, cfg):
    """Top-k MoE FFN. x: [b, s, D]. Dense-einsum formulation: every expert
    computes, weighted by routing probs — the XLA-friendly formulation for
    small expert counts (no ragged dispatch); capacity-based a2a dispatch is
    the scale-out variant (see parallel/moe_dispatch)."""
    b, s, D = x.shape
    router_logits = _wein("bsd,de->bse", x, lp["router"]).astype(jnp.float32)
    probs = jax.nn.softmax(router_logits, axis=-1)
    topk_probs, topk_idx = jax.lax.top_k(probs, cfg.n_experts_active)
    topk_probs = topk_probs / jnp.sum(topk_probs, axis=-1, keepdims=True)
    # weights[b,s,E]: zero except the chosen experts.
    weights = jnp.zeros_like(probs).at[
        jnp.arange(b)[:, None, None],
        jnp.arange(s)[None, :, None],
        topk_idx,
    ].set(topk_probs)
    gate = _wein("bsd,edf->bsef", x, lp["w_gate"])
    up = _wein("bsd,edf->bsef", x, lp["w_up"])
    hidden = _act(cfg)(gate) * up
    out = _wein("bsef,efd->bsed", hidden, lp["w_down"])
    return jnp.einsum("bsed,bse->bsd", out, weights.astype(x.dtype))


def _qkv(h, lp, eq, H, KV, hd, *lead, aids=None):
    """QKV projections with optional Qwen2-style bias (bias leaves exist
    only when cfg.attn_bias — dict membership is trace-time static)."""
    q = _wein(eq, h, lp["wq"]) + _lora(h, lp, "wq", aids)
    k = _wein(eq, h, lp["wk"]) + _lora(h, lp, "wk", aids)
    v = _wein(eq, h, lp["wv"]) + _lora(h, lp, "wv", aids)
    if "wq_b" in lp:
        q = q + lp["wq_b"]
        k = k + lp["wk_b"]
        v = v + lp["wv_b"]
    return (
        q.reshape(*lead, H, hd),
        k.reshape(*lead, KV, hd),
        v.reshape(*lead, KV, hd),
    )


def _layer_prefill(x, lp, cfg, cos, sin, positions, mask, attn_fn=None,
                   lengths=None, norm_out=None, aids=None):
    """One decoder layer over a full sequence. Returns (x, (k, v)).

    attn_fn: optional override for the attention call, e.g. a
    context-parallel (ring/Ulysses) implementation — signature
    ``attn_fn(q, k, v, mask)``. lengths: per-row valid prefix lengths
    (right-padded serving prefill) — keeps the flash-kernel path, unlike
    a dense ``mask``. norm_out: optional sharding hook applied to each
    block's normed input — the Megatron-SP block boundary: the sequence-
    parallel residual all-gathers over tp HERE, so the head sharding of
    q/k/v flows purely from the tp-sharded weights and RoPE's split/
    concat never sees a seq→head reshard (which GSPMD can only do by
    involuntary full rematerialization when n_kv_heads < tp).
    """
    b, s, _ = x.shape
    H, KV, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    h = _norm(x, lp["attn_norm"], cfg, lp.get("attn_norm_b"))
    if norm_out is not None:
        h = norm_out(h)
    q, k, v = _qkv(h, lp, "bsd,dh->bsh", H, KV, hd, b, s, aids=aids)
    if cfg.pos_emb == "rope":
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
    if attn_fn is None:
        attn = attention(
            q, k, v, causal=True, mask=mask, lengths=lengths,
            window=cfg.sliding_window,
        )
    else:
        if cfg.sliding_window:
            raise ValueError(
                "sliding_window is not supported with ring/Ulysses "
                "context-parallel attention"
            )
        attn = attn_fn(q, k, v, mask)
    ao = attn.reshape(b, s, H * hd)
    attn_out = _wein("bsh,hd->bsd", ao, lp["wo"]) + _lora(ao, lp, "wo", aids)
    if "wo_b" in lp:
        attn_out = attn_out + lp["wo_b"]

    # Parallel residual (GPT-NeoX): both branches read the SAME input;
    # sequential (default): the MLP reads the attention-updated stream.
    mlp_in = x if cfg.parallel_residual else x + attn_out
    h = _norm(mlp_in, lp["mlp_norm"], cfg, lp.get("mlp_norm_b"))
    if norm_out is not None:
        h = norm_out(h)
    ffn = _ffn_moe(h, lp, cfg) if cfg.is_moe else _ffn_dense(h, lp, cfg, aids)
    if cfg.parallel_residual:
        return x + attn_out + ffn, (k, v)
    return mlp_in + ffn, (k, v)


# ---------------------------------------------------------------------------
# public forwards
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("cfg", "remat"))
def transformer_forward(
    params: dict,
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    remat: bool = False,
    aids: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Training/eval forward: tokens [b, s] → logits [b, s, vocab] (f32)."""
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    x = _embed(params, tokens, cfg, positions)
    cos, sin = rope_frequencies(cfg.rope_dims, s, cfg.rope_theta)

    def body(x, lp):
        out, _ = _layer_prefill(
            x, lp, cfg, cos, sin, positions, mask=None, aids=aids
        )
        return out, None

    if remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _norm(x, params["final_norm"], cfg, params.get("final_norm_b"))
    return _wein("bsd,dv->bsv", x, params["lm_head"]).astype(jnp.float32)


def transformer_prefill(
    params: dict,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    cache: KVCache,
    slots: jnp.ndarray,
    cfg: TransformerConfig,
    aids: Optional[jnp.ndarray] = None,
) -> tuple[jnp.ndarray, KVCache]:
    """Serving prefill: right-padded prompt batch → last-token logits +
    populated cache.

    tokens: [b, s_pad]; lengths: [b] true lengths; slots: [b] cache slots.
    """
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    x = _embed(params, tokens, cfg, positions)
    cos, sin = rope_frequencies(cfg.rope_dims, cache.max_len, cfg.rope_theta)
    # Per-row lengths mask invalid (right-padding) keys INSIDE the flash
    # kernel — prefill stays on the O(s)-memory kernel path instead of the
    # dense O(s²) masked softmax (VERDICT r1 weak #3).
    lengths = lengths.astype(jnp.int32)

    def body(x, lp):
        out, kv = _layer_prefill(
            x, lp, cfg, cos, sin, positions, mask=None, lengths=lengths,
            aids=aids,
        )
        return out, kv

    x, (ks, vs) = jax.lax.scan(body, x, params["layers"])
    # ks: [L, b, s, KV, hd] → heads-major [L, b, KV, s, hd], pad the seq dim
    # to max_len, write each sequence's prefix into its slot.
    pad_len = cache.max_len - s
    ks = jnp.swapaxes(ks, 2, 3)
    vs = jnp.swapaxes(vs, 2, 3)
    ks = jnp.pad(ks, ((0, 0), (0, 0), (0, 0), (0, pad_len), (0, 0)))
    vs = jnp.pad(vs, ((0, 0), (0, 0), (0, 0), (0, pad_len), (0, 0)))
    if cache.quantized:

        ks, k_sc = quantize_kv(ks)  # scales [L, b, KV, max_len]
        vs, v_sc = quantize_kv(vs)
        rep8 = lambda sc: jnp.broadcast_to(  # noqa: E731
            sc[:, :, :, None, :], sc.shape[:3] + (8,) + sc.shape[3:]
        )
        cache = cache._replace(
            k_s=cache.k_s.at[:, slots].set(rep8(k_sc)),
            v_s=cache.v_s.at[:, slots].set(rep8(v_sc)),
        )
    new_k = cache.k.at[:, slots].set(ks.astype(cache.k.dtype))
    new_v = cache.v.at[:, slots].set(vs.astype(cache.v.dtype))
    cache = cache._replace(k=new_k, v=new_v)
    cache = cache._replace(lengths=cache.lengths.at[slots].set(lengths.astype(jnp.int32)))

    x = _norm(x, params["final_norm"], cfg, params.get("final_norm_b"))
    last_idx = jnp.maximum(lengths - 1, 0)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    logits = _wein("bd,dv->bv", x_last, params["lm_head"]).astype(jnp.float32)
    return logits, cache


def transformer_prefill_chunk(
    params: dict,
    tokens: jnp.ndarray,
    cache: KVCache,
    slots: jnp.ndarray,
    starts: jnp.ndarray,
    lens: jnp.ndarray,
    cfg: TransformerConfig,
    dense_attn: bool = False,
    aids: Optional[jnp.ndarray] = None,
) -> tuple[jnp.ndarray, KVCache]:
    """Chunked serving prefill: one fixed-shape [P, c] chunk step.

    The engine splits prompts into chunks and interleaves chunk steps with
    decode windows (VERDICT r1 weak #9 — admission must not stall decode),
    so serving compiles exactly ONE prefill program regardless of prompt
    length (no bucket ladder). Rows are (slot, start-offset, valid-len)
    tuples; padding rows duplicate row 0 (idempotent duplicate writes).

    tokens: [P, c] chunk token ids (right-padded per row);
    slots/starts/lens: [P] int32 — cache slot, global position of the
    chunk's first token, valid tokens in this chunk.
    Returns ([P, vocab] logits at each row's LAST VALID token, cache).
    ``cache.lengths`` is NOT updated here — the engine sets it when a
    prompt's final chunk lands.
    """
    P, c = tokens.shape
    H, KV, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    positions = starts[:, None] + jnp.arange(c)[None, :]  # [P, c] global
    x = _embed(params, tokens, cfg, positions)  # [P, c, D]
    cos, sin = rope_frequencies(cfg.rope_dims, cache.max_len, cfg.rope_theta)
    paged = isinstance(cache, PagedKVCache)

    idx_kv = jnp.arange(KV)[None, :, None]
    s_kv = jnp.arange(KV)[None, :, None, None]
    s_sub = jnp.arange(8)[None, None, :, None]
    if paged:
        # Map global positions onto (pool block, offset) via the rows'
        # table entries; positions past a row's allocation resolve to the
        # parking block 0 (padding columns only — live prompt positions
        # are allocated ahead by the engine).
        B = cache.block
        bt_rows = cache.block_table[slots]  # [P, max_blocks]
        blk = jnp.take_along_axis(
            bt_rows,
            jnp.minimum(positions // B, bt_rows.shape[1] - 1),
            axis=1,
        )  # [P, c]
        # Padding columns past max_len MUST park in block 0: the slot
        # cache dropped them as out-of-bounds scatter updates, but the
        # min-clamp above would remap them INTO the last real block on
        # top of live prompt K/V.
        in_range = positions < cache.max_len
        blk = jnp.where(in_range, blk, 0)
        off = jnp.where(in_range, positions % B, B - 1)
        idx_row = blk[:, None, :]  # [P, 1, c] pool block per position
        idx_pos = off[:, None, :]
        s_row = blk[:, None, None, :]
        s_pos = off[:, None, None, :]
    else:
        idx_row = slots[:, None, None]
        idx_pos = positions[:, None, :]  # [P, 1, c]
        # Scale-write indices (int8 mode): [S, KV, 8, max_len] layer slice.
        s_row = slots[:, None, None, None]
        s_pos = positions[:, None, None, :]  # [P, 1, 1, c]

    def body(x, scanned):
        lp, ck, cv, cks, cvs = scanned  # ck/cv: [S, KV, max_len, hd]
        h = _norm(x, lp["attn_norm"], cfg, lp.get("attn_norm_b"))
        q, k, v = _qkv(h, lp, "pcd,dh->pch", H, KV, hd, P, c, aids=aids)
        if cfg.pos_emb == "rope":
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
        # Write the chunk's K/V into the cache, then attend against the
        # cache in place (kernel reads only blocks up to starts+lens).
        if cks is not None:

            k, k_sc = quantize_kv(k)  # scales [P, c, KV]
            v, v_sc = quantize_kv(v)
            cks = cks.at[s_row, s_kv, s_sub, s_pos].set(
                k_sc.transpose(0, 2, 1)[:, :, None, :]
            )
            cvs = cvs.at[s_row, s_kv, s_sub, s_pos].set(
                v_sc.transpose(0, 2, 1)[:, :, None, :]
            )
        ck = ck.at[idx_row, idx_kv, idx_pos].set(k.transpose(0, 2, 1, 3))
        cv = cv.at[idx_row, idx_kv, idx_pos].set(v.transpose(0, 2, 1, 3))
        attn = cache_chunk_attention(
            q, ck, cv, slots, starts, lens, k_scale=cks, v_scale=cvs,
            block_table=cache.block_table if paged else None,
            kernel=False if dense_attn else None,
            window=cfg.sliding_window,
        )
        ao = attn.reshape(P, c, H * hd)
        attn_out = (
            _wein("pch,hd->pcd", ao, lp["wo"]) + _lora(ao, lp, "wo", aids)
        )
        if "wo_b" in lp:
            attn_out = attn_out + lp["wo_b"]
        mlp_in = x if cfg.parallel_residual else x + attn_out
        h = _norm(mlp_in, lp["mlp_norm"], cfg, lp.get("mlp_norm_b"))
        ffn = _ffn_moe(h, lp, cfg) if cfg.is_moe else _ffn_dense(
            h, lp, cfg, aids
        )
        x = x + attn_out + ffn if cfg.parallel_residual else mlp_in + ffn
        return x, (ck, cv, cks, cvs)

    x, (new_k, new_v, new_ks, new_vs) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v, cache.k_s, cache.v_s)
    )
    cache = cache._replace(k=new_k, v=new_v, k_s=new_ks, v_s=new_vs)

    x = _norm(x, params["final_norm"], cfg, params.get("final_norm_b"))
    last_idx = jnp.maximum(lens - 1, 0)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    logits = _wein("pd,dv->pv", x_last, params["lm_head"]).astype(jnp.float32)
    return logits, cache


def transformer_decode_step(
    params: dict,
    tokens: jnp.ndarray,
    cache: KVCache,
    active: jnp.ndarray,
    cfg: TransformerConfig,
    dense_attn: bool = False,
    aids: Optional[jnp.ndarray] = None,
) -> tuple[jnp.ndarray, KVCache]:
    """One decode step over ALL cache slots (static batch = n_slots).

    tokens: [n_slots] current token per slot (anything for inactive slots);
    active: [n_slots] bool — only active slots get their K/V write kept and
    their length bumped; inactive rows are wasted FLOPs, which is the right
    trade on TPU (static shapes, no gather/scatter of the cache, the whole
    [L, S, KV, max_len, hd] buffers update in place via donation).
    Returns ([n_slots, vocab] logits, updated cache).
    """
    S = cache.n_slots
    L = cfg.n_layers
    H, KV, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    positions = cache.lengths  # [S] — write position for each slot's new token
    x = _embed(params, tokens, cfg, positions)  # [S, D]
    cos, sin = rope_frequencies(cfg.rope_dims, cache.max_len, cfg.rope_theta)

    # Inactive slots must not write at their stale ``lengths`` position: a
    # slot mid-CHUNKED-prefill has fresh K/V there that a concurrent decode
    # window would corrupt. Park inactive writes at max_len-1 — never
    # attended (admission reserves room so live lengths stay < max_len-1)
    # and rewritten by real decode before it could matter.
    write_pos = jnp.where(active, positions, cache.max_len - 1)
    slot_idx = jnp.arange(S)

    # The cache stays READ-ONLY inside the layer scan: each layer attends
    # the cache prefix + its fresh (k, v) via the split softmax
    # (ops/attention.decode_attention k_new path) and returns the tiny
    # [S, KV, hd] pair as scan ys. One scatter below commits all layers.
    # Round-tripping the full cache through scan ys instead costs ~11 ms
    # of pure HBM copy per step at llama-1b/32 slots (the nested window
    # scan defeats XLA's ys/xs aliasing — scripts/tpu_probe.py).
    paged = isinstance(cache, PagedKVCache)

    def body(x, scanned):
        lp, ck, cv, cks, cvs = scanned  # ck/cv: [S, KV, max_len, hd]
        h = _norm(
            x[:, None, :], lp["attn_norm"], cfg, lp.get("attn_norm_b")
        )[:, 0]
        q, k, v = _qkv(h, lp, "bd,dh->bh", H, KV, hd, S, aids=aids)
        pos2 = positions[:, None]  # [S, 1]
        if cfg.pos_emb == "rope":
            q = apply_rope(q[:, None], cos, sin, pos2)[:, 0]
            k = apply_rope(k[:, None], cos, sin, pos2)[:, 0]
        if cache.quantized:
            # Attend what the cache will hold: fake-quantize the fresh
            # K/V so the split path matches a write-then-attend int8
            # cache bit for bit (commit re-quantizes to the same int8).
            k, v = fake_quantize_kv(k), fake_quantize_kv(v)
        attn = decode_attention(
            q, ck, cv, positions, k_new=k, v_new=v, k_scale=cks,
            v_scale=cvs,
            block_table=cache.block_table if paged else None,
            kernel=False if dense_attn else None,
            window=cfg.sliding_window,
        )
        ao = attn.reshape(S, H * hd)
        attn_out = _wein("bh,hd->bd", ao, lp["wo"]) + _lora(ao, lp, "wo", aids)
        if "wo_b" in lp:
            attn_out = attn_out + lp["wo_b"]
        mlp_in = x if cfg.parallel_residual else x + attn_out
        h = _norm(
            mlp_in[:, None, :], lp["mlp_norm"], cfg, lp.get("mlp_norm_b")
        )
        ffn = _ffn_moe(h, lp, cfg) if cfg.is_moe else _ffn_dense(
            h, lp, cfg, aids
        )
        if cfg.parallel_residual:
            x = x + attn_out + ffn[:, 0]
        else:
            x = mlp_in + ffn[:, 0]
        return x, (k, v)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v, cache.k_s, cache.v_s)
    )
    # Commit every layer's token in one scatter: [L, S, KV, hd] values at
    # [l, s, kv, write_pos[s]] (slot cache) or [l, table[s, p//B], kv,
    # p%B] (paged pool; inactive slots park in block 0) — donation makes
    # this in-place.
    li = jnp.arange(L)[:, None, None]
    ki = jnp.arange(KV)[None, None, :]
    if paged:
        B = cache.block
        blk_log = positions // B
        blk = jnp.take_along_axis(
            cache.block_table, jnp.minimum(blk_log, cache.block_table.shape[1] - 1)[:, None], axis=1
        )[:, 0]
        row = jnp.where(active, blk, 0)[None, :, None]
        wp = jnp.where(active, positions % B, B - 1)[None, :, None]
    else:
        row = slot_idx[None, :, None]
        wp = write_pos[None, :, None]
    if cache.quantized:
        new_k, k_sc = quantize_kv(new_k)  # scales [L, S, KV]
        new_v, v_sc = quantize_kv(new_v)
        sidx = (
            li[..., None], row[..., None], ki[..., None],
            jnp.arange(8)[None, None, None, :], wp[..., None],
        )
        cache = cache._replace(
            k_s=cache.k_s.at[sidx].set(k_sc[..., None]),
            v_s=cache.v_s.at[sidx].set(v_sc[..., None]),
        )
    cache = cache._replace(
        k=cache.k.at[li, row, ki, wp].set(new_k.astype(cache.k.dtype)),
        v=cache.v.at[li, row, ki, wp].set(new_v.astype(cache.v.dtype)),
        lengths=cache.lengths + active.astype(jnp.int32),
    )
    x = _norm(x[:, None, :], params["final_norm"], cfg, params.get("final_norm_b"))[:, 0]
    logits = _wein("bd,dv->bv", x, params["lm_head"]).astype(jnp.float32)
    return logits, cache


def transformer_verify_step(
    params: dict,
    tokens: jnp.ndarray,
    cache: KVCache,
    cfg: TransformerConfig,
    aids: Optional[jnp.ndarray] = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Speculative-verify forward: ``c`` candidate tokens per slot in one
    pass, cache READ-ONLY (rejected drafts need no rollback — the caller
    commits only what it accepts via :func:`commit_chunk_kv`).

    tokens: [S, c] — position j of slot s sits at global position
    ``cache.lengths[s] + j``. Returns (logits [S, c, vocab] f32,
    new_k [L, S, c, KV, hd], new_v [L, S, c, KV, hd]).
    """
    S, c = tokens.shape
    H, KV, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    positions = cache.lengths[:, None] + jnp.arange(c)[None, :]  # [S, c]
    x = _embed(params, tokens, cfg, positions)  # [S, c, D]
    cos, sin = rope_frequencies(cfg.rope_dims, cache.max_len, cfg.rope_theta)
    paged = isinstance(cache, PagedKVCache)
    rows = jnp.arange(S)

    def body(x, scanned):
        lp, ck, cv, cks, cvs = scanned  # read-only cache slices
        h = _norm(x, lp["attn_norm"], cfg, lp.get("attn_norm_b"))
        q, k, v = _qkv(h, lp, "bcd,dh->bch", H, KV, hd, S, c, aids=aids)
        if cfg.pos_emb == "rope":
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
        if cache.quantized:
            # Same fake-quant rule as the decode step: the in-chunk K/V
            # must match what commit_chunk_kv will write, or spec-on
            # output diverges from spec-off under an int8 cache.
            k, v = fake_quantize_kv(k), fake_quantize_kv(v)
        if paged:
            ck, cv, cks, cvs = paged_view(cache.block_table, ck, cv, rows, cks, cvs)
        attn = verify_chunk_attention(
            q, ck, cv, cache.lengths, k, v, k_scale=cks, v_scale=cvs,
            window=cfg.sliding_window,
        )
        ao = attn.reshape(S, c, H * hd)
        attn_out = (
            _wein("bch,hd->bcd", ao, lp["wo"]) + _lora(ao, lp, "wo", aids)
        )
        if "wo_b" in lp:
            attn_out = attn_out + lp["wo_b"]
        mlp_in = x if cfg.parallel_residual else x + attn_out
        h = _norm(mlp_in, lp["mlp_norm"], cfg, lp.get("mlp_norm_b"))
        ffn = _ffn_moe(h, lp, cfg) if cfg.is_moe else _ffn_dense(
            h, lp, cfg, aids
        )
        x = x + attn_out + ffn if cfg.parallel_residual else mlp_in + ffn
        return x, (k, v)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v, cache.k_s, cache.v_s)
    )
    x = _norm(x, params["final_norm"], cfg, params.get("final_norm_b"))
    logits = _wein("bcd,dv->bcv", x, params["lm_head"]).astype(jnp.float32)
    return logits, new_k, new_v


def commit_chunk_kv(
    cache: KVCache,
    new_k: jnp.ndarray,
    new_v: jnp.ndarray,
    active: jnp.ndarray,
    cfg: TransformerConfig,
) -> KVCache:
    """Scatter a verify step's K/V ([L, S, c, KV, hd]) into the cache at
    positions ``lengths + j``. ALL c positions are written — entries past
    the accepted count sit beyond ``lengths`` (the caller advances it by
    accepted+1 only), are never attended, and are overwritten by later
    steps; inactive slots park at max_len-1 like the decode step.
    ``cache.lengths`` is NOT updated here.
    """
    L, S, c, KV, hd = new_k.shape
    pos = cache.lengths[:, None] + jnp.arange(c)[None, :]  # [S, c]
    pos = jnp.where(active[:, None], pos, cache.max_len - 1)
    pos = jnp.minimum(pos, cache.max_len - 1)
    li = jnp.arange(L)[:, None, None, None]
    ki = jnp.arange(KV)[None, None, :, None]
    if isinstance(cache, PagedKVCache):
        B = cache.block
        blk = jnp.take_along_axis(cache.block_table, pos // B, axis=1)
        blk = jnp.where(active[:, None], blk, 0)  # park in block 0
        row = blk[None, :, None, :]  # [1, S, 1, c] pool block ids
        pi = jnp.where(active[:, None], pos % B, B - 1)[None, :, None, :]
    else:
        row = jnp.arange(S)[None, :, None, None]
        pi = pos[None, :, None, :]  # [1, S, 1, c]
    nk = new_k.transpose(0, 1, 3, 2, 4)  # [L, S, KV, c, hd]
    nv = new_v.transpose(0, 1, 3, 2, 4)
    if cache.quantized:
        nk, k_sc = quantize_kv(nk)  # scales [L, S, KV, c]
        nv, v_sc = quantize_kv(nv)
        sidx = (
            li[..., None], row[..., None], ki[..., None],
            jnp.arange(8)[None, None, None, None, :], pi[..., None],
        )
        cache = cache._replace(
            k_s=cache.k_s.at[sidx].set(k_sc[..., None]),
            v_s=cache.v_s.at[sidx].set(v_sc[..., None]),
        )
    return cache._replace(
        k=cache.k.at[li, row, ki, pi].set(nk.astype(cache.k.dtype)),
        v=cache.v.at[li, row, ki, pi].set(nv.astype(cache.v.dtype)),
    )


def ngram_draft(
    history: jnp.ndarray,
    lengths: jnp.ndarray,
    current: jnp.ndarray,
    n_draft: int,
) -> jnp.ndarray:
    """Prompt-lookup drafting: continue the most recent prior occurrence
    of the current context in the slot's own token history.

    history: [S, max_len] int32 (prompt + generated tokens; entries past
    lengths+1 are stale); lengths: [S] tokens in history BEFORE current;
    current: [S] the token about to be fed to the model (already at
    history[lengths]). Matches the bigram (history[p-1], history[p]) ==
    (previous, current) — falling back to a unigram match when the
    context has fewer than 2 tokens — and drafts
    ``history[p+1 : p+1+n_draft]``. No match → repeats ``current``
    (cheap, will simply be rejected). Returns [S, n_draft] int32.
    """
    S, T = history.shape
    pos = jnp.arange(T)[None, :]  # [1, T]
    prev_idx = jnp.maximum(lengths - 1, 0)
    prev = jnp.take_along_axis(history, prev_idx[:, None], axis=1)[:, 0]
    hist_prev = jnp.concatenate(
        [jnp.zeros((S, 1), history.dtype), history[:, :-1]], axis=1
    )
    m1 = history == current[:, None]
    m2 = m1 & (hist_prev == prev[:, None])
    use_bigram = (lengths >= 2)[:, None]
    match = jnp.where(use_bigram, m2, m1)
    # Only positions strictly before the current token's slot qualify.
    match = match & (pos < lengths[:, None])
    p_star = jnp.max(jnp.where(match, pos, -1), axis=1)  # [S]
    found = p_star >= 0
    gidx = jnp.clip(
        p_star[:, None] + 1 + jnp.arange(n_draft)[None, :], 0, T - 1
    )
    draft = jnp.take_along_axis(history, gidx, axis=1)
    return jnp.where(found[:, None], draft, current[:, None])


def count_params(params: dict) -> int:
    """LOGICAL parameter count — a nibble-packed Q4 leaf stores two
    weights per uint8 element, so physical ``.size`` would halve it."""
    from gofr_tpu.ops.quant import Q4, Q8

    total = 0
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, (Q4, Q8))
    ):
        if isinstance(leaf, (Q4, Q8)):
            total += int(np.prod(leaf.shape))  # Q4.shape is logical
        else:
            total += int(leaf.size)
    return total
