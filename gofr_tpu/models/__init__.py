"""Model zoo (net-new; SURVEY §2.6 / BASELINE.json configs).

Families required by BASELINE.json: a Llama-style decoder LM (flagship,
config 5), BERT-style encoder (config 3), and ResNet-50 (config 2) — each a
pure-JAX functional model (init/apply over pytrees) designed for the MXU:
bf16 params, f32 accumulation, scan-over-layers, static shapes.
"""

from gofr_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    transformer_forward,
    transformer_decode_step,
    transformer_prefill,
)
from gofr_tpu.models.registry import get_model, list_models, register_model

__all__ = [
    "TransformerConfig",
    "init_transformer",
    "transformer_forward",
    "transformer_prefill",
    "transformer_decode_step",
    "get_model",
    "list_models",
    "register_model",
]
