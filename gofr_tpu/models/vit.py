"""Vision Transformer (ViT) image classifier — the transformer half of
the vision family next to ResNet (BASELINE config 2's model class).

TPU-first shape: patch embedding is a RESHAPE + one [N, P·P·3]×[P·P·3, D]
matmul (mathematically identical to the stride-P conv, but explicitly a
single large MXU matmul), layers are stacked and scanned like the
decoder (one compiled body regardless of depth), and attention reuses
``ops/attention`` with ``causal=False``. Pre-LN encoder, learned
position embeddings, CLS-token classification head — the ViT-B/16
architecture.

Reference analog: none (GoFr has no models); fills the same serving
slot as ``models/resnet.py`` behind the engine's vision family.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from gofr_tpu.ops.attention import attention
from gofr_tpu.ops.norms import layer_norm


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    num_classes: int = 1000
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_vit(key: jax.Array, cfg: ViTConfig) -> dict:
    def dense(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, dtype=jnp.float32) * fan_in**-0.5
        ).astype(cfg.dtype)

    D, F, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    pdim = cfg.patch_size * cfg.patch_size * 3
    ks = jax.random.split(key, 12)
    layers = {
        "ln1": jnp.ones((L, D), cfg.dtype),
        "ln1_b": jnp.zeros((L, D), cfg.dtype),
        "wq": dense(ks[0], (L, D, D), D),
        "wq_b": jnp.zeros((L, D), cfg.dtype),
        "wk": dense(ks[1], (L, D, D), D),
        "wk_b": jnp.zeros((L, D), cfg.dtype),
        "wv": dense(ks[2], (L, D, D), D),
        "wv_b": jnp.zeros((L, D), cfg.dtype),
        "wo": dense(ks[3], (L, D, D), D),
        "wo_b": jnp.zeros((L, D), cfg.dtype),
        "ln2": jnp.ones((L, D), cfg.dtype),
        "ln2_b": jnp.zeros((L, D), cfg.dtype),
        "w_up": dense(ks[4], (L, D, F), D),
        "w_up_b": jnp.zeros((L, F), cfg.dtype),
        "w_down": dense(ks[5], (L, F, D), F),
        "w_down_b": jnp.zeros((L, D), cfg.dtype),
    }
    return {
        "patch_proj": dense(ks[6], (pdim, D), pdim),
        "patch_proj_b": jnp.zeros((D,), cfg.dtype),
        "cls_token": dense(ks[7], (1, 1, D), D),
        "pos_embed": dense(ks[8], (1 + cfg.n_patches, D), D),
        "layers": layers,
        "ln_f": jnp.ones((D,), cfg.dtype),
        "ln_f_b": jnp.zeros((D,), cfg.dtype),
        "head": dense(ks[9], (D, cfg.num_classes), D),
        "head_b": jnp.zeros((cfg.num_classes,), cfg.dtype),
    }


def patchify(images: jnp.ndarray, patch: int) -> jnp.ndarray:
    """[b, H, W, 3] → [b, N, patch·patch·3], each patch flattened
    row-major (rows, cols, channels) — the order the HF conv kernel
    transposes to in the parity test."""
    b, H, W, C = images.shape
    hp, wp = H // patch, W // patch
    x = images.reshape(b, hp, patch, wp, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [b, hp, wp, patch, patch, C]
    return x.reshape(b, hp * wp, patch * patch * C)


def vit_forward(
    params: dict, images: jnp.ndarray, cfg: ViTConfig
) -> jnp.ndarray:
    """images [b, H, W, 3] (f32) → class logits [b, num_classes] (f32)."""
    b = images.shape[0]
    H, hd = cfg.n_heads, cfg.head_dim
    x = patchify(images.astype(cfg.dtype), cfg.patch_size)
    x = jnp.einsum("bnp,pd->bnd", x, params["patch_proj"])
    x = x + params["patch_proj_b"]
    cls = jnp.broadcast_to(
        params["cls_token"], (b, 1, cfg.d_model)
    ).astype(x.dtype)
    x = jnp.concatenate([cls, x], axis=1)  # [b, 1+N, D]
    x = x + params["pos_embed"]

    def body(x, lp):
        bsz, s, D = x.shape
        h = layer_norm(x, lp["ln1"], lp["ln1_b"], cfg.norm_eps)
        q = (jnp.einsum("bsd,dh->bsh", h, lp["wq"]) + lp["wq_b"]).reshape(
            bsz, s, H, hd
        )
        k = (jnp.einsum("bsd,dh->bsh", h, lp["wk"]) + lp["wk_b"]).reshape(
            bsz, s, H, hd
        )
        v = (jnp.einsum("bsd,dh->bsh", h, lp["wv"]) + lp["wv_b"]).reshape(
            bsz, s, H, hd
        )
        attn = attention(q, k, v, causal=False).reshape(bsz, s, D)
        x = x + jnp.einsum("bsh,hd->bsd", attn, lp["wo"]) + lp["wo_b"]
        h = layer_norm(x, lp["ln2"], lp["ln2_b"], cfg.norm_eps)
        h = jax.nn.gelu(
            jnp.einsum("bsd,df->bsf", h, lp["w_up"]) + lp["w_up_b"],
            approximate=False,
        )
        x = x + jnp.einsum("bsf,fd->bsd", h, lp["w_down"]) + lp["w_down_b"]
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = layer_norm(x, params["ln_f"], params["ln_f_b"], cfg.norm_eps)
    logits = (
        jnp.einsum("bd,dc->bc", x[:, 0], params["head"]) + params["head_b"]
    )
    return logits.astype(jnp.float32)
